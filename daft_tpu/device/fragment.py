"""Fused scan fragments: filter + project + partial aggregation as ONE XLA
program per morsel, with a single packed result transfer.

This is the TPU analogue of the reference's operator fusion inside Swordfish
pipelines (project/filter intermediate ops feeding the grouped-aggregate sink,
``src/daft-local-execution/src/{intermediate_ops,sinks/grouped_aggregate.rs}``)
— but instead of separate operators over channels, the whole chain compiles
into a single jit program: one host→device encode (amortized away entirely by
the HBM column cache for repeated scans), one kernel launch, and ONE
device→host transfer.

The single-transfer discipline matters because the device link is
latency/bandwidth-bound (~36 ms RTT on this tunnel): the aggregate outputs
are sliced device-side to a static group-capacity bucket and bit-packed into
a single int64 matrix, so a whole partial-aggregation result costs one
round-trip regardless of column count. Output dtypes are recorded at trace
time to reverse the packing host-side.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..expressions.expressions import Expression
from ..schema import Schema
from . import column as dcol
from . import compiler, kernels, pallas_kernels, runtime

_fused_cache: Dict[Tuple, object] = {}
_fused_counters: Dict[str, int] = {"hits": 0, "misses": 0}


def fused_cache_counters() -> Dict[str, int]:
    """Fused-agg program cache counters (serving-plane evidence that
    repeated submissions re-enter previously traced device fragments)."""
    out = dict(_fused_counters)
    out["entries"] = len(_fused_cache)
    return out

# static group-capacity buckets for the packed output block: start tiny —
# TPC-H-style aggregations produce a handful of groups, and transferred bytes
# scale with the bucket — and grow geometrically on overflow (the packed
# header always carries the true group count, so overflow costs one re-run).
_OUT_CAP0 = 128


def _pack_i64(x: jnp.ndarray) -> jnp.ndarray:
    """Bit-preserving lowering of any kernel output lane to int64."""
    if x.dtype == jnp.bool_:
        return x.astype(jnp.int64)
    if x.dtype == jnp.float32:
        return lax.bitcast_convert_type(x, jnp.uint32).astype(jnp.int64)
    if x.dtype == jnp.float64:
        return lax.bitcast_convert_type(x, jnp.int64)
    return x.astype(jnp.int64)


def _unpack_i64(row: np.ndarray, dtype) -> np.ndarray:
    """Host-side inverse of :func:`_pack_i64`."""
    dt = np.dtype(dtype)
    if dt == np.bool_:
        return row != 0
    if dt == np.float32:
        return (row & 0xFFFFFFFF).astype(np.uint32).view(np.float32)
    if dt == np.float64:
        return row.view(np.float64)
    return row.astype(dt)


class FusedAggProgram:
    def __init__(self, packed_fn, run_packed, compiled: compiler.Compiled,
                 nk: int, ops: Tuple[str, ...], has_pred: bool, meta: dict):
        self.packed_fn = packed_fn      # single-transfer path (group
        # overflow re-runs it at a grown static out_cap bucket)
        self._run_packed = run_packed   # raw traceable fn — donating twin
        self._donate_fn = None          # lazily jitted with donate_argnums
        self.compiled = compiled
        self.nk = nk
        self.ops = ops
        self.has_pred = has_pred
        self.meta = meta                # trace-time dtype layout
        #: the hash kernel raised (key set packs wider than the table key
        #: budget at trace time) — every later dispatch stays on sort
        self.hash_unfit = False
        #: column → device numpy dtype (set by get_fused_agg; None when
        #: an input is not device-representable) — the AOT warm-up grid
        self.in_np_dtypes = None

    def donate_fn(self):
        """The donating twin executable (round 12 megakernel discipline):
        the encoded input planes are dead after the in-program aggregation,
        so XLA reuses their HBM for the fragment's intermediates — no
        input column survives the dispatch. Only entered for one-shot
        (non-cache-resident) tables on real chips; jitted lazily so CPU
        runs never trace it."""
        if self._donate_fn is None:
            self._donate_fn = jax.jit(
                self._run_packed, static_argnames=("out_cap", "strategy"),
                donate_argnums=(0, 1))
        return self._donate_fn

    def key_plane_dtypes(self):
        """Device dtypes of the group-key planes, for the hash-vs-sort
        strategy width check. String/binary keys ride sorted-dictionary
        codes (int32, ``column._np_encode``); the kernel's own trace
        re-derives the exact pack from the real planes and raises if this
        estimate was too narrow (dispatch sites catch → sort)."""
        out = []
        for f in self.compiled.out_fields[:self.nk]:
            rep = f.dtype.device_repr() \
                if not (f.dtype.is_string() or f.dtype.is_binary()) else None
            out.append(np.dtype(rep) if rep is not None else np.dtype("int32"))
        return out


def get_fused_agg(group_exprs: List[Expression], child_exprs: List[Expression],
                  ops: Tuple[str, ...], predicate: Optional[Expression],
                  schema: Schema) -> Optional[FusedAggProgram]:
    """Compile (or fetch) the fused filter→project→grouped-agg program."""
    key = (tuple(e._key() for e in group_exprs),
           tuple(e._key() for e in child_exprs), ops,
           predicate._key() if predicate is not None else None,
           runtime._schema_key(schema))
    hit = _fused_cache.get(key)
    if hit is not None:
        _fused_counters["hits"] += 1  # GIL-atomic; approximate under race
        return hit if isinstance(hit, FusedAggProgram) else None
    _fused_counters["misses"] += 1
    proj = list(group_exprs) + list(child_exprs) + \
        ([predicate] if predicate is not None else [])
    try:
        c = compiler.compile_projection(proj, schema, jit=False)
    except (compiler.NotCompilable, NotImplementedError, ValueError,
            TypeError, KeyError, OverflowError):
        _fused_cache[key] = False
        return None
    nk = len(group_exprs)
    nv = len(child_exprs)
    has_pred = predicate is not None
    meta: dict = {}

    def eval_inputs(arrays, valids, row_mask, scalars):
        outs = c.fn(arrays, valids, row_mask, scalars)
        if has_pred:
            pv, pm = outs[-1]
            row_mask = row_mask & pv.astype(jnp.bool_) & pm
            outs = outs[:-1]
        keys = tuple(v for v, _ in outs[:nk])
        kvalids = tuple(m for _, m in outs[:nk])
        vals = tuple(v for v, _ in outs[nk:nk + nv])
        vvalids = tuple(m for _, m in outs[nk:nk + nv])
        return keys, kvalids, vals, vvalids, row_mask

    def run_packed(arrays, valids, row_mask, scalars, out_cap: int,
                   strategy: str = "sort"):
        keys, kvalids, vals, vvalids, row_mask = eval_inputs(
            arrays, valids, row_mask, scalars)
        if nk == 0:
            results = kernels.global_agg_impl(vals, vvalids, row_mask, ops)
            flat = [v for v, _ in results] + [m for _, m in results]
            meta["global_dtypes"] = [x.dtype for x in flat]
            return jnp.stack([_pack_i64(x.reshape(())) for x in flat])
        # round 12: the whole scan→filter→project→agg chain stays ONE jit
        # program either way — `strategy` only swaps the reduction's inner
        # loop (one-pass Pallas hash table vs radix sort + segment reduce)
        impl = pallas_kernels.hash_grouped_agg_impl if strategy == "hash" \
            else kernels.grouped_agg_block_impl
        ok, okv, ov, ovv, g = impl(
            keys, kvalids, vals, vvalids, row_mask, ops, out_cap)
        flat = list(ok) + list(okv) + list(ov) + list(ovv)
        meta["grouped_dtypes"] = [x.dtype for x in flat]
        rows = [jnp.full((out_cap,), 0, jnp.int64).at[0]
                .set(g.astype(jnp.int64))]
        rows += [_pack_i64(x) for x in flat]  # already [out_cap]-wide
        return jnp.stack(rows)

    prog = FusedAggProgram(
        jax.jit(run_packed, static_argnames=("out_cap", "strategy")),
        run_packed, c, nk, ops, has_pred, meta)
    try:
        # device input dtypes per needed column — the AOT warm-up grid
        # (device/warmup.py) rebuilds abstract inputs from this
        prog.in_np_dtypes = {
            n: dcol.device_np_dtype(schema[n].dtype)
            for n in c.needs_cols}
    except (ValueError, KeyError):
        prog.in_np_dtypes = None
    _fused_cache[key] = prog
    return prog


def fused_programs() -> List[FusedAggProgram]:
    """Every fused-agg program compiled so far (the 'fragment library'
    the AOT warm-up iterates)."""
    return [p for p in _fused_cache.values()
            if isinstance(p, FusedAggProgram)]


def run_fused_agg(prog: FusedAggProgram, batch, group_exprs, agg_exprs,
                  out_schema: Schema, groups: Optional[float] = None):
    """Execute the fused program on one RecordBatch; returns a RecordBatch of
    partial groups (or None → caller falls back to the host chain)."""
    tok = submit_fused_agg(prog, batch, group_exprs, agg_exprs, out_schema,
                           groups=groups)
    return None if tok is None else drain_fused_agg_table(tok)


def submit_fused_agg(prog: FusedAggProgram, batch, group_exprs, agg_exprs,
                     out_schema: Schema, groups: Optional[float] = None):
    """Pipeline submit half of :func:`run_fused_agg`: host encode +
    asynchronous dispatch of the first ladder rung, NO blocking fetch.
    Returns an in-flight token for :func:`drain_fused_agg_table`, or
    None → host fallback (pyobject inputs)."""
    for nm in prog.compiled.needs_cols:
        if batch.get_column(nm).is_pyobject():
            return None
    dt = dcol.encode_batch(batch, prog.compiled.needs_cols)
    return submit_fused_agg_table(
        prog, dt, batch.schema, group_exprs, agg_exprs, out_schema,
        groups=groups,
        # the donating fast path invalidates the input planes; an overflow
        # re-dispatch re-encodes from the host batch we still hold
        reencode=lambda: dcol.encode_batch(batch, prog.compiled.needs_cols))


def _dispatch_packed(prog: FusedAggProgram, dt: dcol.DeviceTable,
                     out_cap: int, strategy: str = "sort",
                     donate: bool = False):
    from ..analysis import retrace_sanitizer
    arrays = {n: col.data for n, col in dt.columns.items()}
    valids = {n: col.validity for n, col in dt.columns.items()}
    scalars = runtime._prep_scalars(prog.compiled, dt)
    fn = prog.donate_fn() if donate else prog.packed_fn
    # the declared trace signature (dispatch_registry: fragment.packed /
    # fragment.donate) — everything the jit cache key may depend on; a
    # second trace for the SAME key is the retrace tax and a sanitizer
    # budget violation
    with retrace_sanitizer.dispatch_scope(
            "fragment.donate" if donate else "fragment.packed",
            (id(prog), dt.capacity, out_cap, strategy,
             tuple(s.shape for s in scalars))):
        return fn(arrays, valids, dt.row_mask, scalars, out_cap=out_cap,
                  strategy=strategy)


def _donation_ok(dt: dcol.DeviceTable) -> bool:
    """Donate the encoded input planes to the fused program? Never for
    HBM-cache-resident tables (their buffers are SHARED with the cache —
    donating them would poison every later hit) and never on CPU (XLA
    ignores donation there and warns per executable)."""
    from . import backend
    return backend.is_accelerator() and not dt.resident


def gate_strategy(prog: FusedAggProgram, rows: int,
                  groups: Optional[float] = None) -> str:
    """Pricing-only strategy pre-ask for the upload gates (unlogged —
    decision_counts should tally acted-on dispatches, not estimates)."""
    from . import costmodel
    if prog.nk == 0 or prog.hash_unfit:
        return "sort"
    return costmodel.groupby_strategy(rows, groups,
                                      prog.key_plane_dtypes(), _OUT_CAP0,
                                      log=False)[0]


def strategy_for(prog: FusedAggProgram, dt: dcol.DeviceTable, out_cap: int,
                 groups: Optional[float] = None) -> Tuple[str, float]:
    """Hash-vs-sort for one fused-agg dispatch → ``(strategy, load)``.
    Evidence, best-first: the planner's parquet-footer NDV (``groups``),
    else the group-capacity bucket. A program whose key set already proved
    unpackable stays on sort without re-asking. UNLOGGED — the dispatch
    sites call ``costmodel.log_strategy_decision`` once the dispatch
    really ran (a width-gate trace failure can still flip the answer),
    so decision_counts describes what dispatched, not what was asked."""
    from . import costmodel
    if prog.nk == 0 or prog.hash_unfit:
        return "sort", 0.0
    return costmodel.groupby_strategy(dt.row_count, groups,
                                      prog.key_plane_dtypes(), out_cap,
                                      log=False)


def _decode_packed_global(prog: FusedAggProgram, packed: np.ndarray,
                          agg_fields):
    from ..recordbatch import RecordBatch
    dtypes = prog.meta["global_dtypes"]
    nv = len(agg_fields)
    cols = []
    for i, f in enumerate(agg_fields):
        v = _unpack_i64(packed[i:i + 1], dtypes[i])
        m = _unpack_i64(packed[nv + i:nv + i + 1], dtypes[nv + i])
        cols.append(runtime._decode_scalar(f.name, f.dtype, v,
                                           m.astype(np.bool_)))
    return RecordBatch.from_series(cols)


def _decode_packed_grouped(prog: FusedAggProgram, packed: np.ndarray,
                           dt: dcol.DeviceTable, group_exprs, key_fields,
                           agg_fields):
    """Unpack one packed group-block matrix → RecordBatch, or None when the
    group count overflowed the packed capacity (caller re-runs bigger)."""
    from ..recordbatch import RecordBatch
    g = int(packed[0, 0])
    out_cap = packed.shape[1]
    if g > out_cap and out_cap < dt.capacity:
        return None
    dtypes = prog.meta["grouped_dtypes"]
    nk, nv = prog.nk, len(agg_fields)
    rows = packed[1:]
    cols = []
    for i, (e, f) in enumerate(zip(group_exprs, key_fields)):
        kv = _unpack_i64(rows[i][:g], dtypes[i])
        km = _unpack_i64(rows[nk + i][:g], dtypes[nk + i]).astype(np.bool_)
        cols.append(runtime.decode_group_key(e, f, kv, km, dt, g))
    for i, f in enumerate(agg_fields):
        vv = _unpack_i64(rows[2 * nk + i][:g], dtypes[2 * nk + i])
        vm = _unpack_i64(rows[2 * nk + nv + i][:g],
                         dtypes[2 * nk + nv + i]).astype(np.bool_)
        dc = dcol.DeviceColumn(vv, vm, f.dtype, None)
        cols.append(dcol.decode_column(f.name, dc, g))
    return RecordBatch.from_series(cols)


def packed_bytes_per_group(nk: int, nops: int) -> int:
    """Bytes one group row occupies in the packed result matrix (the
    header row amortizes; keys+values each carry a validity plane). The
    executor's cost gates price transfers with this — it must stay in
    lockstep with ``run_packed``'s layout."""
    return (1 + 2 * (nk + nops)) * 8


def _max_out_cap(prog: FusedAggProgram, dt: dcol.DeviceTable) -> int:
    """Group-capacity ceiling from the measured link: the packed-result
    transfer must not exceed what the HOST would spend aggregating the
    same rows outright — a non-reductive grouping (TPC-H Q18's
    near-unique l_orderkey) makes device partials pure freight, while a
    reductive one (Q1's 4 groups) is almost free. Shared-memory links are
    unbounded."""
    import math

    from . import costmodel
    p = costmodel.link_profile()
    full = dcol.bucket_capacity(max(dt.capacity, 1))
    if p.down_bps == math.inf:
        return full
    bytes_per_group = packed_bytes_per_group(prog.nk, len(prog.ops))
    in_bytes = sum(int(c.data.nbytes) + int(c.validity.nbytes)
                   for c in dt.columns.values())
    host_s = in_bytes / costmodel.HOST_AGG_BPS
    raw = int(host_s * p.down_bps // bytes_per_group)
    if raw < _OUT_CAP0:
        return _OUT_CAP0
    # round DOWN to a power of two: dispatch caps are static jit args, so
    # arbitrary integers would compile a fresh executable per value
    return min(1 << (raw.bit_length() - 1), full)


def _ledger_grouped(prog: FusedAggProgram, rows: int, cap: int,
                    out_cap: int, seconds: float, dispatches: int,
                    strategy: str = "sort", load_factor: float = 0.0
                    ) -> None:
    """Per-dispatch MFU accounting for the fused grouped-agg family; the
    byte model follows the strategy the dispatch actually ran."""
    from . import costmodel, mfu
    if strategy == "hash":
        words = pallas_kernels.hash_pack_words(prog.key_plane_dtypes()) or 2
        flops, nbytes = mfu.hash_agg_models(
            cap, out_cap, pallas_kernels.table_capacity(out_cap), words,
            len(prog.ops))
    else:
        flops, nbytes = mfu.grouped_agg_models(cap, out_cap,
                                               max(prog.nk, 1),
                                               len(prog.ops))
    costmodel.ledger_record("grouped_agg", rows=rows,
                            nbytes=dispatches * nbytes,
                            flops=dispatches * flops, seconds=seconds,
                            dispatches=dispatches, strategy=strategy,
                            load_factor=load_factor or None)


class InflightFusedAgg:
    """One in-flight fused-agg dispatch: the device-side packed result
    plus the ladder state a drain needs to finish (overflow re-dispatch,
    per-strategy ledger accounting)."""

    __slots__ = ("prog", "dt", "group_exprs", "key_fields", "agg_fields",
                 "groups", "reencode", "cap_limit", "out_cap", "donate",
                 "strategy", "lf", "packed", "t0", "submitted_s", "acct")

    def __init__(self, prog, dt, group_exprs, key_fields, agg_fields,
                 groups, reencode):
        import time as _time
        self.prog = prog
        self.dt = dt
        self.group_exprs = group_exprs
        self.key_fields = key_fields
        self.agg_fields = agg_fields
        self.groups = groups
        self.reencode = reencode
        self.cap_limit = 0
        self.out_cap = _OUT_CAP0
        self.donate = False
        self.strategy: Optional[str] = None
        self.lf = 0.0
        self.packed = None
        self.t0 = _time.perf_counter()
        #: submit-stage wall (dispatch only) — the ledger charges
        #: submitted_s + drain wall, NOT t0→drain-end, which under the
        #: async window would include time the token sat undrained and
        #: deflate the achieved-GB/s evidence
        self.submitted_s = 0.0
        self.acct: Dict[str, list] = {}  # strategy → [dispatches, lf, cap]


def _ladder_dispatch(tok: InflightFusedAgg) -> None:
    """Dispatch the current ladder rung asynchronously (no fetch),
    handling the hash width-gate fallback and decision logging."""
    from . import costmodel
    while True:
        if tok.strategy is None:
            tok.strategy, tok.lf = strategy_for(tok.prog, tok.dt,
                                                tok.out_cap, tok.groups)
        try:
            tok.packed = _dispatch_packed(tok.prog, tok.dt, tok.out_cap,
                                          tok.strategy, tok.donate)
        except pallas_kernels.HashKeyWidthError:
            # key set packs wider than the hash-table key budget — the
            # kernel's trace is the exact check; remember and re-dispatch
            # on the sort path (donation untouched: the trace failed
            # before any executable could consume the buffers). Any
            # OTHER error propagates — it is a real defect, not a
            # routing signal.
            tok.prog.hash_unfit = True
            tok.strategy, tok.lf = "sort", 0.0
            continue
        tok.acct[tok.strategy] = [
            tok.acct.get(tok.strategy, [0])[0] + 1, tok.lf, tok.out_cap]
        # the decision that actually dispatched (post width-gate fallback)
        costmodel.log_strategy_decision(
            "groupby_strategy", tok.strategy, rows=tok.dt.row_count,
            out_cap=tok.out_cap, load_factor=tok.lf)
        return


def submit_fused_agg_table(prog: FusedAggProgram, dt: dcol.DeviceTable,
                           in_schema: Schema, group_exprs, agg_exprs,
                           out_schema: Schema,
                           start_out_cap: int = _OUT_CAP0,
                           groups: Optional[float] = None, reencode=None
                           ) -> InflightFusedAgg:
    """Async submit half of :func:`run_fused_agg_table`: dispatch the
    first ladder rung and return without blocking on the result — the
    device computes while the caller encodes the next morsel."""
    key_fields = [e.to_field(in_schema) for e in group_exprs]
    agg_fields = [out_schema[e.name()] for e in agg_exprs]
    import time as _time
    tok = InflightFusedAgg(prog, dt, group_exprs, key_fields, agg_fields,
                           groups, reencode)
    if prog.nk == 0:
        tok.packed = _dispatch_packed(prog, dt, _OUT_CAP0)
        tok.submitted_s = _time.perf_counter() - tok.t0
        return tok
    tok.cap_limit = _max_out_cap(prog, dt)
    tok.out_cap = min(start_out_cap, tok.cap_limit)
    tok.donate = reencode is not None and _donation_ok(dt)
    _ladder_dispatch(tok)
    tok.submitted_s = _time.perf_counter() - tok.t0
    return tok


def drain_fused_agg_table(tok: InflightFusedAgg):
    """Blocking drain half: ONE batched fetch of the packed result, then
    decode — continuing the overflow ladder synchronously if the group
    count outgrew the bucket (rare; each retry is dispatch+fetch).
    Returns None → host fallback when groups exceed the link-budgeted
    ceiling."""
    import time as _time

    from . import pipeline
    prog, dt = tok.prog, tok.dt
    t_drain0 = _time.perf_counter()
    if prog.nk == 0:
        packed = np.asarray(pipeline.fetch_host(tok.packed))
        return _decode_packed_global(prog, packed, tok.agg_fields)
    while True:
        packed = np.asarray(pipeline.fetch_host(tok.packed))
        out = _decode_packed_grouped(prog, packed, tok.dt, tok.group_exprs,
                                     tok.key_fields, tok.agg_fields)
        if out is not None:
            # per-strategy accounting: an overflow ladder can MIX
            # strategies (hash saturation falls back to sort), and each
            # family row must count its own dispatches and byte model.
            # The row count and whole-ladder wall go to the completing
            # strategy's record. Submit wall + drain wall — NOT
            # t0→now, which under the async window would charge time
            # the token sat undrained behind its predecessors.
            secs = tok.submitted_s + (_time.perf_counter() - t_drain0)
            for s_, (cnt, l_, oc) in tok.acct.items():
                final = s_ == tok.strategy
                _ledger_grouped(prog, tok.dt.row_count if final else 0,
                                tok.dt.capacity, oc,
                                secs if final else 0.0, cnt, s_, l_)
            return out
        # the packed header carries the group count — TRUE for the sort
        # strategy; the hash strategy saturates at the table size, so a
        # saturated count is only a LOWER bound on the real NDV
        g = int(packed[0, 0])
        if g > tok.cap_limit:
            return None
        if tok.donate:
            tok.dt = tok.reencode()
        saturated = tok.strategy == "hash" \
            and g >= pallas_kernels.table_capacity(tok.out_cap)
        tok.out_cap = min(dcol.bucket_capacity(max(g, _OUT_CAP0)),
                          tok.cap_limit)
        if saturated:
            # a completely full table means the true count is unknown
            # and high — re-dispatch on the sort path, whose header is
            # exact, instead of geometrically doubling the hash bucket
            # one full row pass (and, when donating, one re-encode) at
            # a time; NDV this high is sort's territory anyway
            tok.strategy, tok.lf = "sort", 0.0
        else:
            # the bucket changed: re-ask the strategy model (a grown
            # group budget can push the table past the slot ceiling)
            tok.strategy = None
        _ladder_dispatch(tok)


def run_fused_agg_table(prog: FusedAggProgram, dt: dcol.DeviceTable,
                        in_schema: Schema, group_exprs, agg_exprs,
                        out_schema: Schema, start_out_cap: int = _OUT_CAP0,
                        groups: Optional[float] = None, reencode=None):
    """Execute on one encoded DeviceTable (possibly HBM-cache-resident).
    Returns None (→ host fallback) when the group count exceeds the
    link-budgeted packed-output ceiling. With ``reencode`` (a thunk
    rebuilding the DeviceTable from host data), one-shot tables DONATE
    their input planes to the fused program on real chips — an overflow
    re-dispatch then re-encodes instead of reusing dead buffers.
    (Single-sourced as submit + drain so the async pipeline and the
    synchronous chaos-degradation path run the same ladder.)"""
    return drain_fused_agg_table(submit_fused_agg_table(
        prog, dt, in_schema, group_exprs, agg_exprs, out_schema,
        start_out_cap=start_out_cap, groups=groups, reencode=reencode))


class InflightFusedAggBatch:
    """A window's worth of in-flight fused-agg dispatches (one per
    DeviceTable) awaiting ONE batched pytree fetch."""

    __slots__ = ("prog", "tables", "in_schema", "group_exprs", "agg_exprs",
                 "out_schema", "groups", "key_fields", "agg_fields",
                 "strategy", "lf", "packs", "t0", "submitted_s", "failed")

    def __init__(self, prog, tables, in_schema, group_exprs, agg_exprs,
                 out_schema, groups):
        import time as _time
        self.prog = prog
        self.tables = tables
        self.in_schema = in_schema
        self.group_exprs = group_exprs
        self.agg_exprs = agg_exprs
        self.out_schema = out_schema
        self.groups = groups
        self.key_fields = [e.to_field(in_schema) for e in group_exprs]
        self.agg_fields = [out_schema[e.name()] for e in agg_exprs]
        self.strategy = "sort"
        self.lf = 0.0
        self.packs: list = []
        self.t0 = _time.perf_counter()
        self.submitted_s = 0.0   # dispatch wall (see InflightFusedAgg)
        self.failed = False


def submit_fused_agg_tables(prog: FusedAggProgram, tables,
                            in_schema: Schema, group_exprs, agg_exprs,
                            out_schema: Schema,
                            groups: Optional[float] = None
                            ) -> InflightFusedAggBatch:
    """Async submit half of :func:`run_fused_agg_tables`: dispatch every
    table's fused program (no fetch).  Dispatch failures mark the token
    failed → the drain falls back per-table."""
    import time as _time
    tok = InflightFusedAggBatch(prog, tables, in_schema, group_exprs,
                                agg_exprs, out_schema, groups)
    if not tables:
        return tok
    tok.strategy, tok.lf = strategy_for(prog, tables[0], _OUT_CAP0, groups)
    try:
        tok.packs = [_dispatch_packed(prog, dt, _OUT_CAP0, tok.strategy)
                     for dt in tables]
    except pallas_kernels.HashKeyWidthError:
        prog.hash_unfit = True
        return submit_fused_agg_tables(prog, tables, in_schema,
                                       group_exprs, agg_exprs, out_schema,
                                       groups)
    except Exception:
        tok.failed = True
    tok.submitted_s = _time.perf_counter() - tok.t0
    return tok


def drain_fused_agg_tables(tok: InflightFusedAggBatch):
    """Blocking drain half: ALL packed results come back in a single
    pytree ``device_get`` (one batched transfer for the whole window —
    per-task gets would serialize ~40 ms each on the tunnel), then
    decode; overflowed tables re-dispatch as one batch."""
    import time as _time

    from . import pipeline
    prog, tables = tok.prog, tok.tables
    if not tables:
        return []
    if tok.failed:
        return [None] * len(tables)
    in_schema, group_exprs = tok.in_schema, tok.group_exprs
    agg_exprs, out_schema, groups = tok.agg_exprs, tok.out_schema, tok.groups
    key_fields, agg_fields = tok.key_fields, tok.agg_fields
    strategy, lf = tok.strategy, tok.lf
    t_drain0 = _time.perf_counter()
    try:
        stacked = [np.asarray(m) for m in pipeline.fetch_host(tok.packs)]
    except Exception:
        return [None] * len(tables)
    if prog.nk:
        from . import costmodel
        # ONE decision acted on across the whole batch (post any
        # width-gate recursion above)
        costmodel.log_strategy_decision(
            "groupby_strategy", strategy,
            rows=sum(dt.row_count for dt in tables), out_cap=_OUT_CAP0,
            load_factor=lf, tables=len(tok.packs))
        # submit wall + fetch wall, excluding any in-window queue wait
        # between them (see InflightFusedAgg.submitted_s)
        _ledger_grouped(prog, sum(dt.row_count for dt in tables),
                        max(dt.capacity for dt in tables), _OUT_CAP0,
                        tok.submitted_s
                        + (_time.perf_counter() - t_drain0),
                        len(tok.packs), strategy, lf)
    results: list = [None] * len(tables)
    retry: list = []  # (index, out_cap) — re-dispatched as ONE batch, not
    # per-table (each serial round trip costs ~0.1 s on the tunnel)
    for i, (dt, mat) in enumerate(zip(tables, stacked)):
        try:
            if prog.nk == 0:
                results[i] = _decode_packed_global(prog, mat, agg_fields)
                continue
            out = _decode_packed_grouped(prog, mat, dt, group_exprs,
                                         key_fields, agg_fields)
            if out is not None:
                results[i] = out
                continue
            g = int(mat[0, 0])
            cap_limit = _max_out_cap(prog, dt)
            if g <= cap_limit:  # else: stays None → host fallback
                retry.append((i, min(dcol.bucket_capacity(max(g, _OUT_CAP0)),
                                     cap_limit)))
        except Exception:
            results[i] = None
    if retry:
        # a grown bucket can flip the strategy (table slot ceiling);
        # re-ask per retried table
        retry_strats = [strategy_for(prog, tables[i], cap, groups)
                        for i, cap in retry]
        try:
            packs2 = [_dispatch_packed(prog, tables[i], cap, s)
                      for (i, cap), (s, _l) in zip(retry, retry_strats)]
            mats = [np.asarray(m) for m in pipeline.fetch_host(packs2)]
            from . import costmodel
            for (i, cap), (s, l_) in zip(retry, retry_strats):
                costmodel.log_strategy_decision(
                    "groupby_strategy", s, rows=tables[i].row_count,
                    out_cap=cap, load_factor=l_)
        except Exception:
            mats = [None] * len(retry)
        for (i, _cap), mat in zip(retry, mats):
            if mat is None:
                continue
            try:
                results[i] = _decode_packed_grouped(
                    prog, mat, tables[i], group_exprs, key_fields,
                    agg_fields)
            except Exception:
                results[i] = None
    return results


def run_fused_agg_tables(prog: FusedAggProgram, tables, in_schema: Schema,
                         group_exprs, agg_exprs, out_schema: Schema,
                         groups: Optional[float] = None):
    """Batched execution over many DeviceTables: dispatch every fused
    program asynchronously, then fetch ALL packed results in a single
    batched device→host transfer (one round of transfers for the whole
    scan instead of one per task). Returns a list parallel to ``tables``
    (None → caller falls back per-table). Inputs are never donated here:
    the batched overflow retry re-dispatches over the same tables, and
    cache-resident tables share their buffers with the HBM column cache
    anyway.  (Single-sourced as submit + drain so the async pipeline
    overlaps window N+1's submit with window N's drain.)"""
    return drain_fused_agg_tables(submit_fused_agg_tables(
        prog, tables, in_schema, group_exprs, agg_exprs, out_schema,
        groups))
