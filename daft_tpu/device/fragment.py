"""Fused scan fragments: filter + project + partial aggregation as ONE XLA
program per morsel, with a single packed result transfer.

This is the TPU analogue of the reference's operator fusion inside Swordfish
pipelines (project/filter intermediate ops feeding the grouped-aggregate sink,
``src/daft-local-execution/src/{intermediate_ops,sinks/grouped_aggregate.rs}``)
— but instead of separate operators over channels, the whole chain compiles
into a single jit program: one host→device encode (amortized away entirely by
the HBM column cache for repeated scans), one kernel launch, and ONE
device→host transfer.

The single-transfer discipline matters because the device link is
latency/bandwidth-bound (~36 ms RTT on this tunnel): the aggregate outputs
are sliced device-side to a static group-capacity bucket and bit-packed into
a single int64 matrix, so a whole partial-aggregation result costs one
round-trip regardless of column count. Output dtypes are recorded at trace
time to reverse the packing host-side.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..expressions.expressions import Expression
from ..schema import Schema
from . import column as dcol
from . import compiler, kernels, runtime

_fused_cache: Dict[Tuple, object] = {}
_fused_counters: Dict[str, int] = {"hits": 0, "misses": 0}


def fused_cache_counters() -> Dict[str, int]:
    """Fused-agg program cache counters (serving-plane evidence that
    repeated submissions re-enter previously traced device fragments)."""
    out = dict(_fused_counters)
    out["entries"] = len(_fused_cache)
    return out

# static group-capacity buckets for the packed output block: start tiny —
# TPC-H-style aggregations produce a handful of groups, and transferred bytes
# scale with the bucket — and grow geometrically on overflow (the packed
# header always carries the true group count, so overflow costs one re-run).
_OUT_CAP0 = 128


def _pack_i64(x: jnp.ndarray) -> jnp.ndarray:
    """Bit-preserving lowering of any kernel output lane to int64."""
    if x.dtype == jnp.bool_:
        return x.astype(jnp.int64)
    if x.dtype == jnp.float32:
        return lax.bitcast_convert_type(x, jnp.uint32).astype(jnp.int64)
    if x.dtype == jnp.float64:
        return lax.bitcast_convert_type(x, jnp.int64)
    return x.astype(jnp.int64)


def _unpack_i64(row: np.ndarray, dtype) -> np.ndarray:
    """Host-side inverse of :func:`_pack_i64`."""
    dt = np.dtype(dtype)
    if dt == np.bool_:
        return row != 0
    if dt == np.float32:
        return (row & 0xFFFFFFFF).astype(np.uint32).view(np.float32)
    if dt == np.float64:
        return row.view(np.float64)
    return row.astype(dt)


class FusedAggProgram:
    def __init__(self, packed_fn, compiled: compiler.Compiled, nk: int,
                 ops: Tuple[str, ...], has_pred: bool, meta: dict):
        self.packed_fn = packed_fn      # single-transfer path (group
        # overflow re-runs it at a grown static out_cap bucket)
        self.compiled = compiled
        self.nk = nk
        self.ops = ops
        self.has_pred = has_pred
        self.meta = meta                # trace-time dtype layout


def get_fused_agg(group_exprs: List[Expression], child_exprs: List[Expression],
                  ops: Tuple[str, ...], predicate: Optional[Expression],
                  schema: Schema) -> Optional[FusedAggProgram]:
    """Compile (or fetch) the fused filter→project→grouped-agg program."""
    key = (tuple(e._key() for e in group_exprs),
           tuple(e._key() for e in child_exprs), ops,
           predicate._key() if predicate is not None else None,
           runtime._schema_key(schema))
    hit = _fused_cache.get(key)
    if hit is not None:
        _fused_counters["hits"] += 1  # GIL-atomic; approximate under race
        return hit if isinstance(hit, FusedAggProgram) else None
    _fused_counters["misses"] += 1
    proj = list(group_exprs) + list(child_exprs) + \
        ([predicate] if predicate is not None else [])
    try:
        c = compiler.compile_projection(proj, schema, jit=False)
    except (compiler.NotCompilable, NotImplementedError, ValueError,
            TypeError, KeyError, OverflowError):
        _fused_cache[key] = False
        return None
    nk = len(group_exprs)
    nv = len(child_exprs)
    has_pred = predicate is not None
    meta: dict = {}

    def eval_inputs(arrays, valids, row_mask, scalars):
        outs = c.fn(arrays, valids, row_mask, scalars)
        if has_pred:
            pv, pm = outs[-1]
            row_mask = row_mask & pv.astype(jnp.bool_) & pm
            outs = outs[:-1]
        keys = tuple(v for v, _ in outs[:nk])
        kvalids = tuple(m for _, m in outs[:nk])
        vals = tuple(v for v, _ in outs[nk:nk + nv])
        vvalids = tuple(m for _, m in outs[nk:nk + nv])
        return keys, kvalids, vals, vvalids, row_mask

    def run_packed(arrays, valids, row_mask, scalars, out_cap: int):
        keys, kvalids, vals, vvalids, row_mask = eval_inputs(
            arrays, valids, row_mask, scalars)
        if nk == 0:
            results = kernels.global_agg_impl(vals, vvalids, row_mask, ops)
            flat = [v for v, _ in results] + [m for _, m in results]
            meta["global_dtypes"] = [x.dtype for x in flat]
            return jnp.stack([_pack_i64(x.reshape(())) for x in flat])
        ok, okv, ov, ovv, g = kernels.grouped_agg_block_impl(
            keys, kvalids, vals, vvalids, row_mask, ops, out_cap)
        flat = list(ok) + list(okv) + list(ov) + list(ovv)
        meta["grouped_dtypes"] = [x.dtype for x in flat]
        rows = [jnp.full((out_cap,), 0, jnp.int64).at[0]
                .set(g.astype(jnp.int64))]
        rows += [_pack_i64(x) for x in flat]  # already [out_cap]-wide
        return jnp.stack(rows)

    prog = FusedAggProgram(
        jax.jit(run_packed, static_argnames=("out_cap",)),
        c, nk, ops, has_pred, meta)
    _fused_cache[key] = prog
    return prog


def run_fused_agg(prog: FusedAggProgram, batch, group_exprs, agg_exprs,
                  out_schema: Schema):
    """Execute the fused program on one RecordBatch; returns a RecordBatch of
    partial groups (or None → caller falls back to the host chain)."""
    for nm in prog.compiled.needs_cols:
        if batch.get_column(nm).is_pyobject():
            return None
    dt = dcol.encode_batch(batch, prog.compiled.needs_cols)
    return run_fused_agg_table(prog, dt, batch.schema, group_exprs,
                               agg_exprs, out_schema)


def _dispatch_packed(prog: FusedAggProgram, dt: dcol.DeviceTable,
                     out_cap: int):
    arrays = {n: col.data for n, col in dt.columns.items()}
    valids = {n: col.validity for n, col in dt.columns.items()}
    scalars = runtime._prep_scalars(prog.compiled, dt)
    return prog.packed_fn(arrays, valids, dt.row_mask, scalars,
                          out_cap=out_cap)


def _decode_packed_global(prog: FusedAggProgram, packed: np.ndarray,
                          agg_fields):
    from ..recordbatch import RecordBatch
    dtypes = prog.meta["global_dtypes"]
    nv = len(agg_fields)
    cols = []
    for i, f in enumerate(agg_fields):
        v = _unpack_i64(packed[i:i + 1], dtypes[i])
        m = _unpack_i64(packed[nv + i:nv + i + 1], dtypes[nv + i])
        cols.append(runtime._decode_scalar(f.name, f.dtype, v,
                                           m.astype(np.bool_)))
    return RecordBatch.from_series(cols)


def _decode_packed_grouped(prog: FusedAggProgram, packed: np.ndarray,
                           dt: dcol.DeviceTable, group_exprs, key_fields,
                           agg_fields):
    """Unpack one packed group-block matrix → RecordBatch, or None when the
    group count overflowed the packed capacity (caller re-runs bigger)."""
    from ..recordbatch import RecordBatch
    g = int(packed[0, 0])
    out_cap = packed.shape[1]
    if g > out_cap and out_cap < dt.capacity:
        return None
    dtypes = prog.meta["grouped_dtypes"]
    nk, nv = prog.nk, len(agg_fields)
    rows = packed[1:]
    cols = []
    for i, (e, f) in enumerate(zip(group_exprs, key_fields)):
        kv = _unpack_i64(rows[i][:g], dtypes[i])
        km = _unpack_i64(rows[nk + i][:g], dtypes[nk + i]).astype(np.bool_)
        cols.append(runtime.decode_group_key(e, f, kv, km, dt, g))
    for i, f in enumerate(agg_fields):
        vv = _unpack_i64(rows[2 * nk + i][:g], dtypes[2 * nk + i])
        vm = _unpack_i64(rows[2 * nk + nv + i][:g],
                         dtypes[2 * nk + nv + i]).astype(np.bool_)
        dc = dcol.DeviceColumn(vv, vm, f.dtype, None)
        cols.append(dcol.decode_column(f.name, dc, g))
    return RecordBatch.from_series(cols)


def packed_bytes_per_group(nk: int, nops: int) -> int:
    """Bytes one group row occupies in the packed result matrix (the
    header row amortizes; keys+values each carry a validity plane). The
    executor's cost gates price transfers with this — it must stay in
    lockstep with ``run_packed``'s layout."""
    return (1 + 2 * (nk + nops)) * 8


def _max_out_cap(prog: FusedAggProgram, dt: dcol.DeviceTable) -> int:
    """Group-capacity ceiling from the measured link: the packed-result
    transfer must not exceed what the HOST would spend aggregating the
    same rows outright — a non-reductive grouping (TPC-H Q18's
    near-unique l_orderkey) makes device partials pure freight, while a
    reductive one (Q1's 4 groups) is almost free. Shared-memory links are
    unbounded."""
    import math

    from . import costmodel
    p = costmodel.link_profile()
    full = dcol.bucket_capacity(max(dt.capacity, 1))
    if p.down_bps == math.inf:
        return full
    bytes_per_group = packed_bytes_per_group(prog.nk, len(prog.ops))
    in_bytes = sum(int(c.data.nbytes) + int(c.validity.nbytes)
                   for c in dt.columns.values())
    host_s = in_bytes / costmodel.HOST_AGG_BPS
    raw = int(host_s * p.down_bps // bytes_per_group)
    if raw < _OUT_CAP0:
        return _OUT_CAP0
    # round DOWN to a power of two: dispatch caps are static jit args, so
    # arbitrary integers would compile a fresh executable per value
    return min(1 << (raw.bit_length() - 1), full)


def _ledger_grouped(prog: FusedAggProgram, rows: int, cap: int,
                    out_cap: int, seconds: float, dispatches: int) -> None:
    """Per-dispatch MFU accounting for the fused grouped-agg family."""
    from . import costmodel, mfu
    flops, nbytes = mfu.grouped_agg_models(cap, out_cap, max(prog.nk, 1),
                                           len(prog.ops))
    costmodel.ledger_record("grouped_agg", rows=rows,
                            nbytes=dispatches * nbytes,
                            flops=dispatches * flops, seconds=seconds,
                            dispatches=dispatches)


def run_fused_agg_table(prog: FusedAggProgram, dt: dcol.DeviceTable,
                        in_schema: Schema, group_exprs, agg_exprs,
                        out_schema: Schema, start_out_cap: int = _OUT_CAP0):
    """Execute on one encoded DeviceTable (possibly HBM-cache-resident).
    Returns None (→ host fallback) when the group count exceeds the
    link-budgeted packed-output ceiling."""
    import time as _time
    key_fields = [e.to_field(in_schema) for e in group_exprs]
    agg_fields = [out_schema[e.name()] for e in agg_exprs]
    if prog.nk == 0:
        packed = np.asarray(jax.device_get(
            _dispatch_packed(prog, dt, _OUT_CAP0)))
        return _decode_packed_global(prog, packed, agg_fields)
    cap_limit = _max_out_cap(prog, dt)
    out_cap = min(start_out_cap, cap_limit)
    t0 = _time.perf_counter()
    dispatches = 0
    while True:
        packed = np.asarray(jax.device_get(
            _dispatch_packed(prog, dt, out_cap)))
        dispatches += 1
        out = _decode_packed_grouped(prog, packed, dt, group_exprs,
                                     key_fields, agg_fields)
        if out is not None:
            _ledger_grouped(prog, dt.row_count, dt.capacity, out_cap,
                            _time.perf_counter() - t0, dispatches)
            return out
        # the packed header carries the TRUE group count: jump straight
        # to a fitting bucket, or bail to host when the link can't afford
        # the packed transfer
        g = int(packed[0, 0])
        if g > cap_limit:
            return None
        out_cap = min(dcol.bucket_capacity(max(g, _OUT_CAP0)), cap_limit)


_stack_cache: Dict[int, object] = {}


def _stack(packs):
    n = len(packs)
    fn = _stack_cache.get(n)
    if fn is None:
        fn = jax.jit(lambda *xs: jnp.stack(xs))
        _stack_cache[n] = fn
    return fn(*packs)


def run_fused_agg_tables(prog: FusedAggProgram, tables, in_schema: Schema,
                         group_exprs, agg_exprs, out_schema: Schema):
    """Batched execution over many DeviceTables: dispatch every fused
    program asynchronously, then fetch ALL packed results in a single
    device→host transfer (one RTT for the whole scan instead of one per
    task). Returns a list parallel to ``tables`` (None → caller falls back
    per-table)."""
    import time as _time
    if not tables:
        return []
    key_fields = [e.to_field(in_schema) for e in group_exprs]
    agg_fields = [out_schema[e.name()] for e in agg_exprs]
    t0 = _time.perf_counter()
    try:
        packs = [_dispatch_packed(prog, dt, _OUT_CAP0) for dt in tables]
        stacked = np.asarray(jax.device_get(_stack(packs))) \
            if len(packs) > 1 else [np.asarray(jax.device_get(packs[0]))]
    except Exception:
        return [None] * len(tables)
    if prog.nk:
        _ledger_grouped(prog, sum(dt.row_count for dt in tables),
                        max(dt.capacity for dt in tables), _OUT_CAP0,
                        _time.perf_counter() - t0, len(packs))
    results: list = [None] * len(tables)
    retry: list = []  # (index, out_cap) — re-dispatched as ONE batch, not
    # per-table (each serial round trip costs ~0.1 s on the tunnel)
    for i, (dt, mat) in enumerate(zip(tables, stacked)):
        try:
            if prog.nk == 0:
                results[i] = _decode_packed_global(prog, mat, agg_fields)
                continue
            out = _decode_packed_grouped(prog, mat, dt, group_exprs,
                                         key_fields, agg_fields)
            if out is not None:
                results[i] = out
                continue
            g = int(mat[0, 0])
            cap_limit = _max_out_cap(prog, dt)
            if g <= cap_limit:  # else: stays None → host fallback
                retry.append((i, min(dcol.bucket_capacity(max(g, _OUT_CAP0)),
                                     cap_limit)))
        except Exception:
            results[i] = None
    if retry:
        try:
            packs2 = [_dispatch_packed(prog, tables[i], cap)
                      for i, cap in retry]
            mats = [np.asarray(m) for m in jax.device_get(packs2)]
        except Exception:
            mats = [None] * len(retry)
        for (i, _cap), mat in zip(retry, mats):
            if mat is None:
                continue
            try:
                results[i] = _decode_packed_grouped(
                    prog, mat, tables[i], group_exprs, key_fields,
                    agg_fields)
            except Exception:
                results[i] = None
    return results
