"""Fused scan fragments: filter + project + partial aggregation as ONE XLA
program per morsel.

This is the TPU analogue of the reference's operator fusion inside Swordfish
pipelines (project/filter intermediate ops feeding the grouped-aggregate sink,
``src/daft-local-execution/src/{intermediate_ops,sinks/grouped_aggregate.rs}``)
— but instead of separate operators over channels, the whole chain compiles
into a single jit program: one host→device encode, one kernel launch, one tiny
group-block decode. This minimizes HBM round-trips and compile count.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..expressions.expressions import Expression
from ..schema import Schema
from . import column as dcol
from . import compiler, kernels, runtime


_fused_cache: Dict[Tuple, object] = {}


class FusedAggProgram:
    def __init__(self, fn, compiled: compiler.Compiled, nk: int,
                 ops: Tuple[str, ...], has_pred: bool):
        self.fn = fn
        self.compiled = compiled
        self.nk = nk
        self.ops = ops
        self.has_pred = has_pred


def get_fused_agg(group_exprs: List[Expression], child_exprs: List[Expression],
                  ops: Tuple[str, ...], predicate: Optional[Expression],
                  schema: Schema) -> Optional[FusedAggProgram]:
    """Compile (or fetch) the fused filter→project→grouped-agg program."""
    key = (tuple(e._key() for e in group_exprs),
           tuple(e._key() for e in child_exprs), ops,
           predicate._key() if predicate is not None else None,
           runtime._schema_key(schema))
    hit = _fused_cache.get(key)
    if hit is not None:
        return hit if isinstance(hit, FusedAggProgram) else None
    proj = list(group_exprs) + list(child_exprs) + \
        ([predicate] if predicate is not None else [])
    try:
        c = compiler.compile_projection(proj, schema, jit=False)
    except (compiler.NotCompilable, NotImplementedError, ValueError,
            TypeError, KeyError, OverflowError):
        _fused_cache[key] = False
        return None
    nk = len(group_exprs)
    nv = len(child_exprs)
    has_pred = predicate is not None

    def run(arrays, valids, row_mask, scalars):
        outs = c.fn(arrays, valids, row_mask, scalars)
        if has_pred:
            pv, pm = outs[-1]
            row_mask = row_mask & pv.astype(jnp.bool_) & pm
            outs = outs[:-1]
        keys = tuple(v for v, _ in outs[:nk])
        kvalids = tuple(m for _, m in outs[:nk])
        vals = tuple(v for v, _ in outs[nk:nk + nv])
        vvalids = tuple(m for _, m in outs[nk:nk + nv])
        if nk == 0:
            return kernels.global_agg_impl(vals, vvalids, row_mask, ops)
        return kernels.grouped_agg_impl(keys, kvalids, vals, vvalids,
                                        row_mask, ops)

    prog = FusedAggProgram(jax.jit(run), c, nk, ops, has_pred)
    _fused_cache[key] = prog
    return prog


def run_fused_agg(prog: FusedAggProgram, batch, group_exprs, agg_exprs,
                  out_schema: Schema):
    """Execute the fused program on one RecordBatch; returns a RecordBatch of
    partial groups (or None → caller falls back to the host chain)."""
    from ..recordbatch import RecordBatch
    for nm in prog.compiled.needs_cols:
        if batch.get_column(nm).is_pyobject():
            return None
    dt, arrays, valids, scalars = runtime.encode_for(prog.compiled, batch)

    key_fields = [e.to_field(batch.schema) for e in group_exprs]
    agg_fields = [out_schema[e.name()] for e in agg_exprs]

    if prog.nk == 0:
        results = prog.fn(arrays, valids, dt.row_mask, scalars)
        cols = []
        for f, (rv, rm) in zip(agg_fields, results):
            v = np.asarray(jax.device_get(rv)).reshape(1)
            m = np.asarray(jax.device_get(rm)).reshape(1)
            cols.append(runtime._decode_scalar(f.name, f.dtype, v, m))
        return RecordBatch.from_series(cols)

    out_keys, out_kvalids, out_vals, out_valids, gcount = \
        prog.fn(arrays, valids, dt.row_mask, scalars)
    g = int(jax.device_get(gcount))
    cols = []
    for e, f, kv, km in zip(group_exprs, key_fields, out_keys, out_kvalids):
        cols.append(runtime.decode_group_key(e, f, kv, km, dt, g))
    for f, vv, vm in zip(agg_fields, out_vals, out_valids):
        dc = dcol.DeviceColumn(vv, vm, f.dtype, None)
        cols.append(dcol.decode_column(f.name, dc, g))
    return RecordBatch.from_series(cols)
