"""Fused scan fragments: filter + project + partial aggregation as ONE XLA
program per morsel, with a single packed result transfer.

This is the TPU analogue of the reference's operator fusion inside Swordfish
pipelines (project/filter intermediate ops feeding the grouped-aggregate sink,
``src/daft-local-execution/src/{intermediate_ops,sinks/grouped_aggregate.rs}``)
— but instead of separate operators over channels, the whole chain compiles
into a single jit program: one host→device encode (amortized away entirely by
the HBM column cache for repeated scans), one kernel launch, and ONE
device→host transfer.

The single-transfer discipline matters because the device link is
latency/bandwidth-bound (~36 ms RTT on this tunnel): the aggregate outputs
are sliced device-side to a static group-capacity bucket and bit-packed into
a single int64 matrix, so a whole partial-aggregation result costs one
round-trip regardless of column count. Output dtypes are recorded at trace
time to reverse the packing host-side.
"""

from __future__ import annotations

import threading as _threading
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..expressions.expressions import Expression
from ..schema import Schema
from . import column as dcol
from . import compiler, kernels, pallas_kernels, runtime

_fused_cache: Dict[Tuple, object] = {}
_fused_counters: Dict[str, int] = {"hits": 0, "misses": 0}


def fused_cache_counters() -> Dict[str, int]:
    """Fused-agg program cache counters (serving-plane evidence that
    repeated submissions re-enter previously traced device fragments)."""
    out = dict(_fused_counters)
    out["entries"] = len(_fused_cache)
    return out

# static group-capacity buckets for the packed output block: start tiny —
# TPC-H-style aggregations produce a handful of groups, and transferred bytes
# scale with the bucket — and grow geometrically on overflow (the packed
# header always carries the true group count, so overflow costs one re-run).
_OUT_CAP0 = 128


def _pack_i64(x: jnp.ndarray) -> jnp.ndarray:
    """Bit-preserving lowering of any kernel output lane to int64."""
    if x.dtype == jnp.bool_:
        return x.astype(jnp.int64)
    if x.dtype == jnp.float32:
        return lax.bitcast_convert_type(x, jnp.uint32).astype(jnp.int64)
    if x.dtype == jnp.float64:
        return lax.bitcast_convert_type(x, jnp.int64)
    return x.astype(jnp.int64)


def _unpack_i64(row: np.ndarray, dtype) -> np.ndarray:
    """Host-side inverse of :func:`_pack_i64`."""
    dt = np.dtype(dtype)
    if dt == np.bool_:
        return row != 0
    if dt == np.float32:
        return (row & 0xFFFFFFFF).astype(np.uint32).view(np.float32)
    if dt == np.float64:
        return row.view(np.float64)
    return row.astype(dt)


class FusedAggProgram:
    def __init__(self, packed_fn, run_packed, compiled: compiler.Compiled,
                 nk: int, ops: Tuple[str, ...], has_pred: bool, meta: dict):
        self.packed_fn = packed_fn      # single-transfer path (group
        # overflow re-runs it at a grown static out_cap bucket)
        self._run_packed = run_packed   # raw traceable fn — donating twin
        self._donate_fn = None          # lazily jitted with donate_argnums
        self.compiled = compiled
        self.nk = nk
        self.ops = ops
        self.has_pred = has_pred
        self.meta = meta                # trace-time dtype layout
        #: the hash kernel raised (key set packs wider than the table key
        #: budget at trace time) — every later dispatch stays on sort
        self.hash_unfit = False
        #: column → device numpy dtype (set by get_fused_agg; None when
        #: an input is not device-representable) — the AOT warm-up grid
        self.in_np_dtypes = None
        #: source column per group key when EVERY key is a string/binary
        #: passthrough (dictionary-coded plane) — dense-strategy
        #: eligibility; None otherwise
        self.key_sources = None

    def donate_fn(self):
        """The donating twin executable (round 12 megakernel discipline):
        the encoded input planes are dead after the in-program aggregation,
        so XLA reuses their HBM for the fragment's intermediates — no
        input column survives the dispatch. Only entered for one-shot
        (non-cache-resident) tables on real chips; jitted lazily so CPU
        runs never trace it."""
        if self._donate_fn is None:
            self._donate_fn = jax.jit(
                self._run_packed,
                static_argnames=("out_cap", "strategy", "dims"),
                donate_argnums=(0, 1))
        return self._donate_fn

    def key_plane_dtypes(self):
        """Device dtypes of the group-key planes, for the hash-vs-sort
        strategy width check. String/binary keys ride sorted-dictionary
        codes (int32, ``column._np_encode``); the kernel's own trace
        re-derives the exact pack from the real planes and raises if this
        estimate was too narrow (dispatch sites catch → sort)."""
        out = []
        for f in self.compiled.out_fields[:self.nk]:
            rep = f.dtype.device_repr() \
                if not (f.dtype.is_string() or f.dtype.is_binary()) else None
            out.append(np.dtype(rep) if rep is not None else np.dtype("int32"))
        return out


def get_fused_agg(group_exprs: List[Expression], child_exprs: List[Expression],
                  ops: Tuple[str, ...], predicate: Optional[Expression],
                  schema: Schema) -> Optional[FusedAggProgram]:
    """Compile (or fetch) the fused filter→project→grouped-agg program."""
    key = (tuple(e._key() for e in group_exprs),
           tuple(e._key() for e in child_exprs), ops,
           predicate._key() if predicate is not None else None,
           runtime._schema_key(schema))
    hit = _fused_cache.get(key)
    if hit is not None:
        _fused_counters["hits"] += 1  # GIL-atomic; approximate under race
        return hit if isinstance(hit, FusedAggProgram) else None
    _fused_counters["misses"] += 1
    proj = list(group_exprs) + list(child_exprs) + \
        ([predicate] if predicate is not None else [])
    try:
        c = compiler.compile_projection(proj, schema, jit=False)
    except (compiler.NotCompilable, NotImplementedError, ValueError,
            TypeError, KeyError, OverflowError):
        _fused_cache[key] = False
        return None
    nk = len(group_exprs)
    nv = len(child_exprs)
    has_pred = predicate is not None
    meta: dict = {}

    def eval_inputs(arrays, valids, row_mask, scalars):
        outs = c.fn(arrays, valids, row_mask, scalars)
        if has_pred:
            pv, pm = outs[-1]
            row_mask = row_mask & pv.astype(jnp.bool_) & pm
            outs = outs[:-1]
        keys = tuple(v for v, _ in outs[:nk])
        kvalids = tuple(m for _, m in outs[:nk])
        vals = tuple(v for v, _ in outs[nk:nk + nv])
        vvalids = tuple(m for _, m in outs[nk:nk + nv])
        return keys, kvalids, vals, vvalids, row_mask

    def run_packed(arrays, valids, row_mask, scalars, out_cap: int,
                   strategy: str = "sort", dims: Tuple[int, ...] = ()):
        keys, kvalids, vals, vvalids, row_mask = eval_inputs(
            arrays, valids, row_mask, scalars)
        if nk == 0:
            results = kernels.global_agg_impl(vals, vvalids, row_mask, ops)
            flat = [v for v, _ in results] + [m for _, m in results]
            meta["global_dtypes"] = [x.dtype for x in flat]
            return jnp.stack([_pack_i64(x.reshape(())) for x in flat])
        # round 12: the whole scan→filter→project→agg chain stays ONE jit
        # program either way — `strategy` only swaps the reduction's inner
        # loop (dense direct slot indexing vs one-pass Pallas hash table
        # vs radix sort + segment reduce)
        if strategy == "dense":
            ok, okv, ov, ovv, g = kernels.grouped_agg_dense_impl(
                keys, kvalids, vals, vvalids, row_mask, ops, out_cap, dims)
        else:
            impl = pallas_kernels.hash_grouped_agg_impl \
                if strategy == "hash" else kernels.grouped_agg_block_impl
            ok, okv, ov, ovv, g = impl(
                keys, kvalids, vals, vvalids, row_mask, ops, out_cap)
        flat = list(ok) + list(okv) + list(ov) + list(ovv)
        meta["grouped_dtypes"] = [x.dtype for x in flat]
        rows = [jnp.full((out_cap,), 0, jnp.int64).at[0]
                .set(g.astype(jnp.int64))]
        rows += [_pack_i64(x) for x in flat]  # already [out_cap]-wide
        return jnp.stack(rows)

    prog = FusedAggProgram(
        jax.jit(run_packed, static_argnames=("out_cap", "strategy", "dims")),
        run_packed, c, nk, ops, has_pred, meta)
    # dense-strategy eligibility: every group key must be a plain
    # string/binary column passthrough, so its device plane carries
    # sorted-dictionary codes the mixed-radix group id can index directly
    srcs = []
    for e, f in zip(group_exprs, c.out_fields[:nk]):
        src = runtime._string_out_source(e) \
            if (f.dtype.is_string() or f.dtype.is_binary()) else None
        if src is None:
            srcs = None
            break
        srcs.append(src)
    prog.key_sources = tuple(srcs) if srcs else None
    try:
        # device input dtypes per needed column — the AOT warm-up grid
        # (device/warmup.py) rebuilds abstract inputs from this
        prog.in_np_dtypes = {
            n: dcol.device_np_dtype(schema[n].dtype)
            for n in c.needs_cols}
    except (ValueError, KeyError):
        prog.in_np_dtypes = None
    _fused_cache[key] = prog
    return prog


def fused_programs() -> List[FusedAggProgram]:
    """Every fused-agg program compiled so far (the 'fragment library'
    the AOT warm-up iterates)."""
    return [p for p in _fused_cache.values()
            if isinstance(p, FusedAggProgram)]


def run_fused_agg(prog: FusedAggProgram, batch, group_exprs, agg_exprs,
                  out_schema: Schema, groups: Optional[float] = None):
    """Execute the fused program on one RecordBatch; returns a RecordBatch of
    partial groups (or None → caller falls back to the host chain)."""
    tok = submit_fused_agg(prog, batch, group_exprs, agg_exprs, out_schema,
                           groups=groups)
    return None if tok is None else drain_fused_agg_table(tok)


def submit_fused_agg(prog: FusedAggProgram, batch, group_exprs, agg_exprs,
                     out_schema: Schema, groups: Optional[float] = None):
    """Pipeline submit half of :func:`run_fused_agg`: host encode +
    asynchronous dispatch of the first ladder rung, NO blocking fetch.
    Returns an in-flight token for :func:`drain_fused_agg_table`, or
    None → host fallback (pyobject inputs)."""
    for nm in prog.compiled.needs_cols:
        if batch.get_column(nm).is_pyobject():
            return None
    dt = dcol.encode_batch(batch, prog.compiled.needs_cols)
    return submit_fused_agg_table(
        prog, dt, batch.schema, group_exprs, agg_exprs, out_schema,
        groups=groups,
        # the donating fast path invalidates the input planes; an overflow
        # re-dispatch re-encodes from the host batch we still hold
        reencode=lambda: dcol.encode_batch(batch, prog.compiled.needs_cols))


def _dispatch_packed(prog: FusedAggProgram, dt: dcol.DeviceTable,
                     out_cap: int, strategy: str = "sort",
                     donate: bool = False, dims: Tuple[int, ...] = ()):
    from ..analysis import retrace_sanitizer
    arrays = {n: col.data for n, col in dt.columns.items()}
    valids = {n: col.validity for n, col in dt.columns.items()}
    scalars = runtime._prep_scalars(prog.compiled, dt)
    fn = prog.donate_fn() if donate else prog.packed_fn
    # the declared trace signature (dispatch_registry: fragment.packed /
    # fragment.donate) — everything the jit cache key may depend on; a
    # second trace for the SAME key is the retrace tax and a sanitizer
    # budget violation
    with retrace_sanitizer.dispatch_scope(
            "fragment.donate" if donate else "fragment.packed",
            (id(prog), dt.capacity, out_cap, strategy, dims,
             tuple(s.shape for s in scalars))):
        return fn(arrays, valids, dt.row_mask, scalars, out_cap=out_cap,
                  strategy=strategy, dims=dims)


#: dense-strategy slot ceiling: K = prod(dim+1) static slots per dispatch;
#: past this the slot planes outgrow the group blocks they stand in for
#: and hash/sort territory begins anyway
DENSE_MAX_SLOTS = 4096


def dense_dims(prog: FusedAggProgram,
               dt: dcol.DeviceTable) -> Optional[Tuple[int, ...]]:
    """Pow2-bucketed dictionary width per group key, or None when this
    table is ineligible for the dense direct-index strategy (a key is not
    a dictionary-coded passthrough, a dictionary is missing, or the slot
    product exceeds :data:`DENSE_MAX_SLOTS`). Bucketing to powers of two
    bounds the static-arg space: per-morsel dictionaries drift in size,
    but their buckets — and therefore the traced programs — do not."""
    if not prog.key_sources:
        return None
    dims = []
    K = 1
    for src in prog.key_sources:
        col = dt.columns.get(src)
        if col is None or col.dictionary is None:
            return None
        d = len(col.dictionary)
        d = max(1 << (max(d - 1, 0)).bit_length(), 1)  # pow2 ceiling
        dims.append(d)
        K *= d + 1
        if K > DENSE_MAX_SLOTS:
            return None
    return tuple(dims)


def dense_plan(prog: FusedAggProgram, dt: dcol.DeviceTable,
               cap_limit: int) -> Optional[Tuple[Tuple[int, ...], int]]:
    """``(dims, out_cap)`` for a dense dispatch, or None when ineligible.
    The bucket is sized to hold every possible slot up front — dense
    output can never overflow, so the ladder never re-dispatches."""
    dims = dense_dims(prog, dt)
    if dims is None:
        return None
    K = 1
    for d in dims:
        K *= d + 1
    out_cap = dcol.bucket_capacity(max(K, _OUT_CAP0))
    if out_cap > cap_limit:
        return None
    return dims, out_cap


def _donation_ok(dt: dcol.DeviceTable) -> bool:
    """Donate the encoded input planes to the fused program? Never for
    HBM-cache-resident tables (their buffers are SHARED with the cache —
    donating them would poison every later hit) and never on CPU (XLA
    ignores donation there and warns per executable)."""
    from . import backend
    return backend.is_accelerator() and not dt.resident


def gate_strategy(prog: FusedAggProgram, rows: int,
                  groups: Optional[float] = None) -> str:
    """Pricing-only strategy pre-ask for the upload gates (unlogged —
    decision_counts should tally acted-on dispatches, not estimates)."""
    from . import costmodel
    if prog.nk == 0 or prog.hash_unfit:
        return "sort"
    return costmodel.groupby_strategy(rows, groups,
                                      prog.key_plane_dtypes(), _OUT_CAP0,
                                      log=False)[0]


def strategy_for(prog: FusedAggProgram, dt: dcol.DeviceTable, out_cap: int,
                 groups: Optional[float] = None) -> Tuple[str, float]:
    """Hash-vs-sort for one fused-agg dispatch → ``(strategy, load)``.
    Evidence, best-first: the planner's parquet-footer NDV (``groups``),
    else the group-capacity bucket. A program whose key set already proved
    unpackable stays on sort without re-asking. UNLOGGED — the dispatch
    sites call ``costmodel.log_strategy_decision`` once the dispatch
    really ran (a width-gate trace failure can still flip the answer),
    so decision_counts describes what dispatched, not what was asked."""
    from . import costmodel
    if prog.nk == 0 or prog.hash_unfit:
        return "sort", 0.0
    return costmodel.groupby_strategy(dt.row_count, groups,
                                      prog.key_plane_dtypes(), out_cap,
                                      log=False)


def _decode_packed_global(prog: FusedAggProgram, packed: np.ndarray,
                          agg_fields):
    from ..recordbatch import RecordBatch
    dtypes = prog.meta["global_dtypes"]
    nv = len(agg_fields)
    cols = []
    for i, f in enumerate(agg_fields):
        v = _unpack_i64(packed[i:i + 1], dtypes[i])
        m = _unpack_i64(packed[nv + i:nv + i + 1], dtypes[nv + i])
        cols.append(runtime._decode_scalar(f.name, f.dtype, v,
                                           m.astype(np.bool_)))
    return RecordBatch.from_series(cols)


def _decode_packed_grouped(prog: FusedAggProgram, packed: np.ndarray,
                           dt: dcol.DeviceTable, group_exprs, key_fields,
                           agg_fields):
    """Unpack one packed group-block matrix → RecordBatch, or None when the
    group count overflowed the packed capacity (caller re-runs bigger)."""
    from ..recordbatch import RecordBatch
    g = int(packed[0, 0])
    out_cap = packed.shape[1]
    if g > out_cap and out_cap < dt.capacity:
        return None
    dtypes = prog.meta["grouped_dtypes"]
    nk, nv = prog.nk, len(agg_fields)
    rows = packed[1:]
    cols = []
    for i, (e, f) in enumerate(zip(group_exprs, key_fields)):
        kv = _unpack_i64(rows[i][:g], dtypes[i])
        km = _unpack_i64(rows[nk + i][:g], dtypes[nk + i]).astype(np.bool_)
        cols.append(runtime.decode_group_key(e, f, kv, km, dt, g))
    for i, f in enumerate(agg_fields):
        vv = _unpack_i64(rows[2 * nk + i][:g], dtypes[2 * nk + i])
        vm = _unpack_i64(rows[2 * nk + nv + i][:g],
                         dtypes[2 * nk + nv + i]).astype(np.bool_)
        dc = dcol.DeviceColumn(vv, vm, f.dtype, None)
        cols.append(dcol.decode_column(f.name, dc, g))
    return RecordBatch.from_series(cols)


def packed_bytes_per_group(nk: int, nops: int) -> int:
    """Bytes one group row occupies in the packed result matrix (the
    header row amortizes; keys+values each carry a validity plane). The
    executor's cost gates price transfers with this — it must stay in
    lockstep with ``run_packed``'s layout."""
    return (1 + 2 * (nk + nops)) * 8


def _max_out_cap(prog: FusedAggProgram, dt: dcol.DeviceTable) -> int:
    """Group-capacity ceiling from the measured link: the packed-result
    transfer must not exceed what the HOST would spend aggregating the
    same rows outright — a non-reductive grouping (TPC-H Q18's
    near-unique l_orderkey) makes device partials pure freight, while a
    reductive one (Q1's 4 groups) is almost free. Shared-memory links are
    unbounded."""
    import math

    from . import costmodel
    p = costmodel.link_profile()
    full = dcol.bucket_capacity(max(dt.capacity, 1))
    if p.down_bps == math.inf:
        return full
    bytes_per_group = packed_bytes_per_group(prog.nk, len(prog.ops))
    in_bytes = sum(int(c.data.nbytes) + int(c.validity.nbytes)
                   for c in dt.columns.values())
    host_s = in_bytes / costmodel.HOST_AGG_BPS
    raw = int(host_s * p.down_bps // bytes_per_group)
    if raw < _OUT_CAP0:
        return _OUT_CAP0
    # round DOWN to a power of two: dispatch caps are static jit args, so
    # arbitrary integers would compile a fresh executable per value
    return min(1 << (raw.bit_length() - 1), full)


def _ledger_grouped(prog: FusedAggProgram, rows: int, cap: int,
                    out_cap: int, seconds: float, dispatches: int,
                    strategy: str = "sort", load_factor: float = 0.0
                    ) -> None:
    """Per-dispatch MFU accounting for the fused grouped-agg family; the
    byte model follows the strategy the dispatch actually ran."""
    from . import costmodel, mfu
    if strategy == "hash":
        words = pallas_kernels.hash_pack_words(prog.key_plane_dtypes()) or 2
        flops, nbytes = mfu.hash_agg_models(
            cap, out_cap, pallas_kernels.table_capacity(out_cap), words,
            len(prog.ops))
    elif strategy == "dense":
        flops, nbytes = mfu.dense_agg_models(cap, out_cap,
                                             max(prog.nk, 1),
                                             len(prog.ops))
    else:
        flops, nbytes = mfu.grouped_agg_models(cap, out_cap,
                                               max(prog.nk, 1),
                                               len(prog.ops))
    costmodel.ledger_record("grouped_agg", rows=rows,
                            nbytes=dispatches * nbytes,
                            flops=dispatches * flops, seconds=seconds,
                            dispatches=dispatches, strategy=strategy,
                            load_factor=load_factor or None)


class InflightFusedAgg:
    """One in-flight fused-agg dispatch: the device-side packed result
    plus the ladder state a drain needs to finish (overflow re-dispatch,
    per-strategy ledger accounting)."""

    __slots__ = ("prog", "dt", "group_exprs", "key_fields", "agg_fields",
                 "groups", "reencode", "cap_limit", "out_cap", "donate",
                 "strategy", "lf", "dims", "packed", "t0", "submitted_s",
                 "acct")

    def __init__(self, prog, dt, group_exprs, key_fields, agg_fields,
                 groups, reencode):
        import time as _time
        self.prog = prog
        self.dt = dt
        self.group_exprs = group_exprs
        self.key_fields = key_fields
        self.agg_fields = agg_fields
        self.groups = groups
        self.reencode = reencode
        self.cap_limit = 0
        self.out_cap = _OUT_CAP0
        self.donate = False
        self.strategy: Optional[str] = None
        self.lf = 0.0
        self.dims: Tuple[int, ...] = ()
        self.packed = None
        self.t0 = _time.perf_counter()
        #: submit-stage wall (dispatch only) — the ledger charges
        #: submitted_s + drain wall, NOT t0→drain-end, which under the
        #: async window would include time the token sat undrained and
        #: deflate the achieved-GB/s evidence
        self.submitted_s = 0.0
        self.acct: Dict[str, list] = {}  # strategy → [dispatches, lf, cap]


def _ladder_dispatch(tok: InflightFusedAgg) -> None:
    """Dispatch the current ladder rung asynchronously (no fetch),
    handling the hash width-gate fallback and decision logging."""
    from . import costmodel
    while True:
        if tok.strategy is None:
            # dense first: a direct-indexed dispatch streams the rows once
            # with no sort and no table, so whenever the key dictionaries
            # fit the slot budget it dominates both rivals
            plan = dense_plan(tok.prog, tok.dt, tok.cap_limit)
            if plan is not None:
                tok.dims, tok.out_cap = plan
                tok.strategy, tok.lf = "dense", 0.0
            else:
                tok.dims = ()
                tok.strategy, tok.lf = strategy_for(tok.prog, tok.dt,
                                                    tok.out_cap, tok.groups)
        try:
            tok.packed = _dispatch_packed(tok.prog, tok.dt, tok.out_cap,
                                          tok.strategy, tok.donate,
                                          tok.dims)
        except pallas_kernels.HashKeyWidthError:
            # key set packs wider than the hash-table key budget — the
            # kernel's trace is the exact check; remember and re-dispatch
            # on the sort path (donation untouched: the trace failed
            # before any executable could consume the buffers). Any
            # OTHER error propagates — it is a real defect, not a
            # routing signal.
            tok.prog.hash_unfit = True
            tok.strategy, tok.lf = "sort", 0.0
            continue
        tok.acct[tok.strategy] = [
            tok.acct.get(tok.strategy, [0])[0] + 1, tok.lf, tok.out_cap]
        # the decision that actually dispatched (post width-gate fallback)
        costmodel.log_strategy_decision(
            "groupby_strategy", tok.strategy, rows=tok.dt.row_count,
            out_cap=tok.out_cap, load_factor=tok.lf)
        return


def submit_fused_agg_table(prog: FusedAggProgram, dt: dcol.DeviceTable,
                           in_schema: Schema, group_exprs, agg_exprs,
                           out_schema: Schema,
                           start_out_cap: int = _OUT_CAP0,
                           groups: Optional[float] = None, reencode=None
                           ) -> InflightFusedAgg:
    """Async submit half of :func:`run_fused_agg_table`: dispatch the
    first ladder rung and return without blocking on the result — the
    device computes while the caller encodes the next morsel."""
    key_fields = [e.to_field(in_schema) for e in group_exprs]
    agg_fields = [out_schema[e.name()] for e in agg_exprs]
    import time as _time
    tok = InflightFusedAgg(prog, dt, group_exprs, key_fields, agg_fields,
                           groups, reencode)
    if prog.nk == 0:
        tok.packed = _dispatch_packed(prog, dt, _OUT_CAP0)
        tok.submitted_s = _time.perf_counter() - tok.t0
        return tok
    tok.cap_limit = _max_out_cap(prog, dt)
    tok.out_cap = min(start_out_cap, tok.cap_limit)
    tok.donate = reencode is not None and _donation_ok(dt)
    _ladder_dispatch(tok)
    tok.submitted_s = _time.perf_counter() - tok.t0
    return tok


def drain_fused_agg_table(tok: InflightFusedAgg):
    """Blocking drain half: ONE batched fetch of the packed result, then
    decode — continuing the overflow ladder synchronously if the group
    count outgrew the bucket (rare; each retry is dispatch+fetch).
    Returns None → host fallback when groups exceed the link-budgeted
    ceiling."""
    import time as _time

    from . import pipeline
    prog, dt = tok.prog, tok.dt
    t_drain0 = _time.perf_counter()
    if prog.nk == 0:
        packed = np.asarray(pipeline.fetch_host(tok.packed))
        return _decode_packed_global(prog, packed, tok.agg_fields)
    while True:
        packed = np.asarray(pipeline.fetch_host(tok.packed))
        out = _decode_packed_grouped(prog, packed, tok.dt, tok.group_exprs,
                                     tok.key_fields, tok.agg_fields)
        if out is not None:
            # per-strategy accounting: an overflow ladder can MIX
            # strategies (hash saturation falls back to sort), and each
            # family row must count its own dispatches and byte model.
            # The row count and whole-ladder wall go to the completing
            # strategy's record. Submit wall + drain wall — NOT
            # t0→now, which under the async window would charge time
            # the token sat undrained behind its predecessors.
            secs = tok.submitted_s + (_time.perf_counter() - t_drain0)
            for s_, (cnt, l_, oc) in tok.acct.items():
                final = s_ == tok.strategy
                _ledger_grouped(prog, tok.dt.row_count if final else 0,
                                tok.dt.capacity, oc,
                                secs if final else 0.0, cnt, s_, l_)
            return out
        # the packed header carries the group count — TRUE for the sort
        # strategy; the hash strategy saturates at the table size, so a
        # saturated count is only a LOWER bound on the real NDV
        g = int(packed[0, 0])
        if g > tok.cap_limit:
            return None
        if tok.donate:
            tok.dt = tok.reencode()
        saturated = tok.strategy == "hash" \
            and g >= pallas_kernels.table_capacity(tok.out_cap)
        tok.out_cap = min(dcol.bucket_capacity(max(g, _OUT_CAP0)),
                          tok.cap_limit)
        if saturated:
            # a completely full table means the true count is unknown
            # and high — re-dispatch on the sort path, whose header is
            # exact, instead of geometrically doubling the hash bucket
            # one full row pass (and, when donating, one re-encode) at
            # a time; NDV this high is sort's territory anyway
            tok.strategy, tok.lf = "sort", 0.0
        else:
            # the bucket changed: re-ask the strategy model (a grown
            # group budget can push the table past the slot ceiling)
            tok.strategy = None
        _ladder_dispatch(tok)


def run_fused_agg_table(prog: FusedAggProgram, dt: dcol.DeviceTable,
                        in_schema: Schema, group_exprs, agg_exprs,
                        out_schema: Schema, start_out_cap: int = _OUT_CAP0,
                        groups: Optional[float] = None, reencode=None):
    """Execute on one encoded DeviceTable (possibly HBM-cache-resident).
    Returns None (→ host fallback) when the group count exceeds the
    link-budgeted packed-output ceiling. With ``reencode`` (a thunk
    rebuilding the DeviceTable from host data), one-shot tables DONATE
    their input planes to the fused program on real chips — an overflow
    re-dispatch then re-encodes instead of reusing dead buffers.
    (Single-sourced as submit + drain so the async pipeline and the
    synchronous chaos-degradation path run the same ladder.)"""
    return drain_fused_agg_table(submit_fused_agg_table(
        prog, dt, in_schema, group_exprs, agg_exprs, out_schema,
        start_out_cap=start_out_cap, groups=groups, reencode=reencode))


class InflightFusedAggBatch:
    """A window's worth of in-flight fused-agg dispatches (one per
    DeviceTable) awaiting ONE batched pytree fetch."""

    __slots__ = ("prog", "tables", "in_schema", "group_exprs", "agg_exprs",
                 "out_schema", "groups", "key_fields", "agg_fields",
                 "strategy", "lf", "packs", "t0", "submitted_s", "failed")

    def __init__(self, prog, tables, in_schema, group_exprs, agg_exprs,
                 out_schema, groups):
        import time as _time
        self.prog = prog
        self.tables = tables
        self.in_schema = in_schema
        self.group_exprs = group_exprs
        self.agg_exprs = agg_exprs
        self.out_schema = out_schema
        self.groups = groups
        self.key_fields = [e.to_field(in_schema) for e in group_exprs]
        self.agg_fields = [out_schema[e.name()] for e in agg_exprs]
        self.strategy = "sort"
        self.lf = 0.0
        self.packs: list = []
        self.t0 = _time.perf_counter()
        self.submitted_s = 0.0   # dispatch wall (see InflightFusedAgg)
        self.failed = False


def submit_fused_agg_tables(prog: FusedAggProgram, tables,
                            in_schema: Schema, group_exprs, agg_exprs,
                            out_schema: Schema,
                            groups: Optional[float] = None
                            ) -> InflightFusedAggBatch:
    """Async submit half of :func:`run_fused_agg_tables`: dispatch every
    table's fused program (no fetch).  Dispatch failures mark the token
    failed → the drain falls back per-table."""
    import time as _time
    tok = InflightFusedAggBatch(prog, tables, in_schema, group_exprs,
                                agg_exprs, out_schema, groups)
    if not tables:
        return tok
    # dense first, per table: each morsel carries its own dictionaries
    # (pow2-bucketed, so same-scan tables share one traced program); a
    # table that misses the slot budget rides the batch strategy instead
    plans = [dense_plan(prog, dt, _max_out_cap(prog, dt)) for dt in tables]
    if all(p is not None for p in plans):
        tok.strategy, tok.lf = "dense", 0.0
        try:
            tok.packs = [
                _dispatch_packed(prog, dt, p[1], "dense", dims=p[0])
                for dt, p in zip(tables, plans)]
            tok.submitted_s = _time.perf_counter() - tok.t0
            return tok
        except Exception:
            tok.packs = []  # fall through to the hash/sort batch path
    tok.strategy, tok.lf = strategy_for(prog, tables[0], _OUT_CAP0, groups)
    try:
        tok.packs = [_dispatch_packed(prog, dt, _OUT_CAP0, tok.strategy)
                     for dt in tables]
    except pallas_kernels.HashKeyWidthError:
        prog.hash_unfit = True
        return submit_fused_agg_tables(prog, tables, in_schema,
                                       group_exprs, agg_exprs, out_schema,
                                       groups)
    except Exception:
        tok.failed = True
    tok.submitted_s = _time.perf_counter() - tok.t0
    return tok


def drain_fused_agg_tables(tok: InflightFusedAggBatch):
    """Blocking drain half: ALL packed results come back in a single
    pytree ``device_get`` (one batched transfer for the whole window —
    per-task gets would serialize ~40 ms each on the tunnel), then
    decode; overflowed tables re-dispatch as one batch."""
    import time as _time

    from . import pipeline
    prog, tables = tok.prog, tok.tables
    if not tables:
        return []
    if tok.failed:
        return [None] * len(tables)
    in_schema, group_exprs = tok.in_schema, tok.group_exprs
    agg_exprs, out_schema, groups = tok.agg_exprs, tok.out_schema, tok.groups
    key_fields, agg_fields = tok.key_fields, tok.agg_fields
    strategy, lf = tok.strategy, tok.lf
    t_drain0 = _time.perf_counter()
    try:
        stacked = [np.asarray(m) for m in pipeline.fetch_host(tok.packs)]
    except Exception:
        return [None] * len(tables)
    if prog.nk:
        from . import costmodel
        # ONE decision acted on across the whole batch (post any
        # width-gate recursion above)
        costmodel.log_strategy_decision(
            "groupby_strategy", strategy,
            rows=sum(dt.row_count for dt in tables), out_cap=_OUT_CAP0,
            load_factor=lf, tables=len(tok.packs))
        # submit wall + fetch wall, excluding any in-window queue wait
        # between them (see InflightFusedAgg.submitted_s)
        _ledger_grouped(prog, sum(dt.row_count for dt in tables),
                        max(dt.capacity for dt in tables), _OUT_CAP0,
                        tok.submitted_s
                        + (_time.perf_counter() - t_drain0),
                        len(tok.packs), strategy, lf)
    results: list = [None] * len(tables)
    retry: list = []  # (index, out_cap) — re-dispatched as ONE batch, not
    # per-table (each serial round trip costs ~0.1 s on the tunnel)
    for i, (dt, mat) in enumerate(zip(tables, stacked)):
        try:
            if prog.nk == 0:
                results[i] = _decode_packed_global(prog, mat, agg_fields)
                continue
            out = _decode_packed_grouped(prog, mat, dt, group_exprs,
                                         key_fields, agg_fields)
            if out is not None:
                results[i] = out
                continue
            g = int(mat[0, 0])
            cap_limit = _max_out_cap(prog, dt)
            if g <= cap_limit:  # else: stays None → host fallback
                retry.append((i, min(dcol.bucket_capacity(max(g, _OUT_CAP0)),
                                     cap_limit)))
        except Exception:
            results[i] = None
    if retry:
        # a grown bucket can flip the strategy (table slot ceiling);
        # re-ask per retried table
        retry_strats = [strategy_for(prog, tables[i], cap, groups)
                        for i, cap in retry]
        try:
            packs2 = [_dispatch_packed(prog, tables[i], cap, s)
                      for (i, cap), (s, _l) in zip(retry, retry_strats)]
            mats = [np.asarray(m) for m in pipeline.fetch_host(packs2)]
            from . import costmodel
            for (i, cap), (s, l_) in zip(retry, retry_strats):
                costmodel.log_strategy_decision(
                    "groupby_strategy", s, rows=tables[i].row_count,
                    out_cap=cap, load_factor=l_)
        except Exception:
            mats = [None] * len(retry)
        for (i, _cap), mat in zip(retry, mats):
            if mat is None:
                continue
            try:
                results[i] = _decode_packed_grouped(
                    prog, mat, tables[i], group_exprs, key_fields,
                    agg_fields)
            except Exception:
                results[i] = None
    return results


def run_fused_agg_tables(prog: FusedAggProgram, tables, in_schema: Schema,
                         group_exprs, agg_exprs, out_schema: Schema,
                         groups: Optional[float] = None):
    """Batched execution over many DeviceTables: dispatch every fused
    program asynchronously, then fetch ALL packed results in a single
    batched device→host transfer (one round of transfers for the whole
    scan instead of one per task). Returns a list parallel to ``tables``
    (None → caller falls back per-table). Inputs are never donated here:
    the batched overflow retry re-dispatches over the same tables, and
    cache-resident tables share their buffers with the HBM column cache
    anyway.  (Single-sourced as submit + drain so the async pipeline
    overlaps window N+1's submit with window N's drain.)"""
    return drain_fused_agg_tables(submit_fused_agg_tables(
        prog, tables, in_schema, group_exprs, agg_exprs, out_schema,
        groups))


# ---------------------------------------------------------------------------
# FusedRegion programs (round 21 whole-query compilation)
#
# A FusedRegion compiles a maximal operator chain — filter/project chains,
# top-k tails, and join→project→partial-agg spines — into ONE traced
# program whose intermediates stay device-resident; only the region's
# packed output crosses the link. Three program families mirror the three
# planner grammars (physical/fusion.py):
#
# - chain: predicate + projection + in-program compaction; the survivors
#   transfer at a static width bucket (overflow re-dispatches grown, the
#   grouped-agg ladder discipline).
# - topk: a chain whose tail argsort runs in-program; only a static
#   ``bucket_capacity(limit)`` slice transfers, never the full table.
# - join_agg: the broadcast build side is encoded + radix-sorted ONCE and
#   stays resident; each probe morsel runs predicate → searchsorted join →
#   joined-plane gather → post-projection → partial grouped agg as one
#   dispatch, with DUAL overflow ladders (join pair width W and group
#   bucket out_cap), both read from the packed header.

_region_cache: Dict[Tuple, object] = {}

#: join pair-width ceiling: past this the fused join's expand planes cost
#: more HBM than the morsel itself and the host join is the right tool
_REGION_MAX_W = 1 << 22


class FusedRegionProgram:
    """One compiled fusion region (chain or topk shape)."""

    def __init__(self, shape: str, packed_fn, run_packed,
                 compiled: compiler.Compiled,
                 nout: int, has_pred: bool, meta: dict,
                 fused_ops: Tuple[str, ...] = (), limit: int = 0):
        self.shape = shape              # chain | topk
        self.packed_fn = packed_fn
        self._run_packed = run_packed
        self._donate_fn = None
        self.compiled = compiled
        self.nout = nout
        self.has_pred = has_pred
        self.meta = meta
        self.fused_ops = fused_ops
        self.limit = limit
        self.in_np_dtypes = None
        #: per input-capacity survivor bucket observed on the last drain:
        #: the ladder's learned first rung (benign race: worst case one
        #: extra overflow re-dispatch)
        self.w_hint: Dict[int, int] = {}

    def donate_fn(self):
        """Donating twin (r12 discipline): one-shot input planes are dead
        after the in-program compaction, so XLA reuses their HBM. Guarded
        by ``_donation_ok`` — never for cache-resident tables, never on
        CPU."""
        if self._donate_fn is None:
            self._donate_fn = jax.jit(
                self._run_packed, static_argnames=("out_w",),
                donate_argnums=(0, 1))
        return self._donate_fn


def get_fused_region(exprs, predicate, schema: Schema,
                     sort_by=(), descending=(), nulls_first=(),
                     limit: Optional[int] = None,
                     fused_ops: Tuple[str, ...] = ()
                     ) -> Optional[FusedRegionProgram]:
    """Compile (or fetch) a chain/topk region program. None → the region
    does not lower (caller runs the fallback subtree)."""
    shape = "topk" if sort_by else "chain"
    key = ("region", shape, tuple(e._key() for e in exprs),
           predicate._key() if predicate is not None else None,
           tuple(e._key() for e in sort_by), tuple(descending),
           tuple(nulls_first), limit, runtime._schema_key(schema))
    hit = _region_cache.get(key)
    if hit is not None:
        _fused_counters["hits"] += 1
        return hit if isinstance(hit, FusedRegionProgram) else None
    _fused_counters["misses"] += 1
    proj = list(exprs) + list(sort_by) + \
        ([predicate] if predicate is not None else [])
    try:
        c = compiler.compile_projection(proj, schema, jit=False)
    except (compiler.NotCompilable, NotImplementedError, ValueError,
            TypeError, KeyError, OverflowError):
        _region_cache[key] = False
        return None
    n = len(exprs)
    ns = len(sort_by)
    has_pred = predicate is not None
    desc = tuple(bool(d) for d in descending)
    nf = tuple(bool(x) for x in nulls_first)
    k_lim = int(limit or 0)
    meta: dict = {}

    def run_packed(arrays, valids, row_mask, scalars, out_w: int):
        outs = c.fn(arrays, valids, row_mask, scalars)
        if has_pred:
            pv, pm = outs[-1]
            row_mask = row_mask & pv.astype(jnp.bool_) & pm
            outs = outs[:-1]
        # encode_batch capacities are bucket_capacity outputs already;
        # min(shape, bucket(shape)) re-asserts that through the
        # sanctioned chokepoint without ever changing the value
        C = min(row_mask.shape[0], dcol.bucket_capacity(row_mask.shape[0]))
        live = jnp.sum(row_mask).astype(jnp.int32)
        if ns:
            skeys = tuple(v for v, _ in outs[n:])
            svalids = tuple(m for _, m in outs[n:])
            perm = kernels._packed_argsort(
                kernels._sort_codes(skeys, svalids, row_mask, desc, nf), C)
            live = jnp.minimum(live, jnp.asarray(k_lim, jnp.int32))
            outs = outs[:n]
        else:
            # stable compaction: live rows to the front in source order
            perm = lax.sort(((~row_mask).astype(jnp.int8),
                             jnp.arange(C, dtype=jnp.int32)),
                            num_keys=1, is_stable=True)[1]
        w = min(out_w, C)
        idx = perm[:w]
        sel = jnp.arange(w, dtype=jnp.int32) < live
        flat = [jnp.take(v, idx) for v, _ in outs] \
            + [jnp.take(m, idx) & sel for _, m in outs]
        meta["region_dtypes"] = [x.dtype for x in flat]
        rows = [jnp.zeros((w,), jnp.int64).at[0].set(live.astype(jnp.int64))]
        rows += [_pack_i64(x) for x in flat]
        return jnp.stack(rows)

    prog = FusedRegionProgram(
        shape, jax.jit(run_packed, static_argnames=("out_w",)),
        run_packed, c, n, has_pred, meta, fused_ops=fused_ops, limit=k_lim)
    try:
        prog.in_np_dtypes = {nm: dcol.device_np_dtype(schema[nm].dtype)
                             for nm in c.needs_cols}
    except (ValueError, KeyError):
        prog.in_np_dtypes = None
    _region_cache[key] = prog
    return prog


def region_start_w(prog: FusedRegionProgram, dt: dcol.DeviceTable) -> int:
    """First transfer-width rung. Top-k transfers its static k bucket;
    an unfiltered chain can never shrink, so it transfers whole; a
    filtered chain bets on selectivity with a quarter-capacity bucket —
    one overflow re-dispatch costs a dispatch, not a scan."""
    if prog.shape == "topk":
        return min(dcol.bucket_capacity(max(prog.limit, 1)), dt.capacity)
    if not prog.has_pred:
        return dt.capacity
    hint = prog.w_hint.get(dt.capacity)
    if hint is not None:
        # learned rung: the last morsel at this capacity drained at this
        # survivor bucket — steady-state selectivity makes it right for
        # the next one, turning the ladder into a one-dispatch path
        return min(hint, dt.capacity)
    return min(dcol.bucket_capacity(
        max(min(dt.capacity, dt.row_count) // 4, _OUT_CAP0)), dt.capacity)


class InflightRegion:
    """One in-flight chain/topk region dispatch awaiting its packed
    fetch (+ the ladder state an overflow re-dispatch needs)."""

    __slots__ = ("prog", "dt", "exprs", "fields", "out_w", "donate",
                 "reencode", "packed", "t0", "submitted_s")

    def __init__(self, prog, dt, exprs, fields, out_w, donate, reencode):
        import time as _time
        self.prog = prog
        self.dt = dt
        self.exprs = exprs
        self.fields = fields
        self.out_w = out_w
        self.donate = donate
        self.reencode = reencode
        self.packed = None
        self.t0 = _time.perf_counter()
        self.submitted_s = 0.0


def _dispatch_region(prog: FusedRegionProgram, dt: dcol.DeviceTable,
                     out_w: int, donate: bool = False):
    from ..analysis import retrace_sanitizer
    arrays = {n: col.data for n, col in dt.columns.items()}
    valids = {n: col.validity for n, col in dt.columns.items()}
    scalars = runtime._prep_scalars(prog.compiled, dt)
    fn = prog.donate_fn() if donate else prog.packed_fn
    with retrace_sanitizer.dispatch_scope(
            "region.topk" if prog.shape == "topk" else "region.chain",
            (id(prog), dt.capacity, out_w,
             tuple(s.shape for s in scalars))):
        return fn(arrays, valids, dt.row_mask, scalars, out_w=out_w)


def submit_region(prog: FusedRegionProgram, batch, exprs, out_schema: Schema
                  ) -> Optional[InflightRegion]:
    """Encode + async dispatch of one chain/topk region morsel; None →
    host fallback (pyobject inputs / encode failure)."""
    import time as _time
    for nm in prog.compiled.needs_cols:
        if batch.get_column(nm).is_pyobject():
            return None
    try:
        dt = dcol.encode_batch(batch, prog.compiled.needs_cols)
    except (ValueError, TypeError):
        return None
    fields = [out_schema[e.name()] for e in exprs]
    donate = _donation_ok(dt)
    tok = InflightRegion(prog, dt, exprs, fields,
                         region_start_w(prog, dt), donate,
                         lambda: dcol.encode_batch(
                             batch, prog.compiled.needs_cols))
    tok.packed = _dispatch_region(prog, dt, tok.out_w, donate=donate)
    tok.submitted_s = _time.perf_counter() - tok.t0
    return tok


def drain_region(tok: InflightRegion):
    """Blocking drain: one packed fetch → RecordBatch, continuing the
    width ladder when a chain's survivor count outgrew the bucket."""
    import time as _time

    from . import costmodel, pipeline
    prog = tok.prog
    t_drain0 = _time.perf_counter()
    while True:
        packed = np.asarray(pipeline.fetch_host(tok.packed))
        live = int(packed[0, 0])
        w = packed.shape[1]
        if live <= w:
            from ..recordbatch import RecordBatch
            dtypes = prog.meta["region_dtypes"]
            nout = prog.nout
            rows = packed[1:]
            cols = []
            for i, (e, f) in enumerate(zip(tok.exprs, tok.fields)):
                v = _unpack_i64(rows[i][:live], dtypes[i])
                m = _unpack_i64(rows[nout + i][:live],
                                dtypes[nout + i]).astype(np.bool_)
                cols.append(runtime.decode_group_key(e, f, v, m, tok.dt,
                                                     live))
            out = RecordBatch.from_series(cols)
            if prog.has_pred and prog.shape != "topk":
                prog.w_hint[tok.dt.capacity] = min(
                    dcol.bucket_capacity(max(live, _OUT_CAP0)),
                    tok.dt.capacity)
            n_ops = max(len(prog.fused_ops), 2)
            secs = tok.submitted_s + (_time.perf_counter() - t_drain0)
            costmodel.ledger_record(
                "region", rows=tok.dt.row_count,
                nbytes=(1 + 2 * nout) * 8 * w, seconds=secs,
                strategy=prog.shape, fused_ops=n_ops,
                round_trips_saved=n_ops - 1,
                fusion_serial_seconds=costmodel.fusion_serial_estimate(
                    tok.dt.row_count, n_ops))
            return out
        if tok.donate:
            tok.dt = tok.reencode()
            tok.donate = False
        tok.out_w = min(dcol.bucket_capacity(live), tok.dt.capacity)
        tok.packed = _dispatch_region(prog, tok.dt, tok.out_w)


class FusedJoinAggProgram:
    """One compiled join_agg region: probe predicate → searchsorted join
    against the pre-sorted resident build side → joined-plane gather →
    post projection → partial grouped agg, as ONE traced program."""

    def __init__(self, packed_fn, run_packed, c_pred, c_post,
                 lkey: str, rkey: str,
                 probe_needs, build_needs, nk: int, ops: Tuple[str, ...],
                 has_post_pred: bool, meta: dict,
                 fused_ops: Tuple[str, ...] = ()):
        self.packed_fn = packed_fn
        self._run_packed = run_packed
        self.c_pred = c_pred            # probe-side predicate (or None)
        self.c_post = c_post            # joined-namespace projection
        self.lkey = lkey
        self.rkey = rkey
        self.probe_needs = probe_needs  # raw probe planes the gather feeds
        self.build_needs = build_needs
        self.nk = nk
        self.ops = ops
        self.has_post_pred = has_post_pred
        self.meta = meta
        self.fused_ops = fused_ops
        self.in_np_dtypes = None        # probe-side planes (warm-up grid)
        self.build_np_dtypes = None     # build-side planes (warm-up grid)


class RegionBuild:
    """The join_agg build side, encoded + radix-sorted once per query;
    every probe morsel's program reuses these resident planes."""

    __slots__ = ("dt", "sorted_key", "perm", "live_count")

    def __init__(self, dt, sorted_key, perm, live_count):
        self.dt = dt
        self.sorted_key = sorted_key
        self.perm = perm
        self.live_count = live_count


_join_sort_jit = None
_join_sort_lock = _threading.Lock()


def prepare_region_build(prog: FusedJoinAggProgram, build_rb
                         ) -> Optional[RegionBuild]:
    """Encode the broadcast build side and sort its join-key plane —
    ONE dispatch for the whole query. None → region declines."""
    global _join_sort_jit
    from ..analysis import retrace_sanitizer
    cols = list(dict.fromkeys([prog.rkey] + list(prog.build_needs)))
    for nm in cols:
        if build_rb.get_column(nm).is_pyobject():
            return None
    try:
        dt = dcol.encode_batch(build_rb, cols)
    except (ValueError, TypeError):
        return None
    if _join_sort_jit is None:
        with _join_sort_lock:
            if _join_sort_jit is None:
                _join_sort_jit = jax.jit(kernels.join_sort_impl)
    k = dt.columns[prog.rkey]
    with retrace_sanitizer.dispatch_scope("region.build",
                                          (dt.capacity,)):
        sorted_key, perm, live = _join_sort_jit(k.data, k.validity,
                                                dt.row_mask)
    return RegionBuild(dt, sorted_key, perm, live)


def get_fused_join_agg(group_exprs, child_exprs, ops: Tuple[str, ...],
                       probe_pred, post_pred, lkey: str, rkey: str,
                       src_schema: Schema, build_schema: Schema,
                       fused_ops: Tuple[str, ...] = ()
                       ) -> Optional[FusedJoinAggProgram]:
    """Compile (or fetch) the join_agg region program. None → the region
    does not lower."""
    key = ("region_ja", tuple(e._key() for e in group_exprs),
           tuple(e._key() for e in child_exprs), ops,
           probe_pred._key() if probe_pred is not None else None,
           post_pred._key() if post_pred is not None else None,
           lkey, rkey, runtime._schema_key(src_schema),
           runtime._schema_key(build_schema))
    hit = _region_cache.get(key)
    if hit is not None:
        _fused_counters["hits"] += 1
        return hit if isinstance(hit, FusedJoinAggProgram) else None
    _fused_counters["misses"] += 1
    from ..schema import Field
    src_names = set(src_schema.column_names)
    joined_schema = Schema(
        [Field(f.name, f.dtype) for f in src_schema]
        + [Field(f.name, f.dtype) for f in build_schema])
    nk = len(group_exprs)
    has_post_pred = post_pred is not None
    proj = list(group_exprs) + list(child_exprs) + \
        ([post_pred] if post_pred is not None else [])
    try:
        c_post = compiler.compile_projection(proj, joined_schema, jit=False)
        c_pred = compiler.compile_projection([probe_pred], src_schema,
                                             jit=False) \
            if probe_pred is not None else None
    except (compiler.NotCompilable, NotImplementedError, ValueError,
            TypeError, KeyError, OverflowError):
        _region_cache[key] = False
        return None
    probe_needs = tuple(nm for nm in c_post.needs_cols if nm in src_names)
    build_needs = tuple(nm for nm in c_post.needs_cols
                        if nm not in src_names)
    meta: dict = {}

    def run_packed(p_arrays, p_valids, p_mask, p_scalars,
                   b_arrays, b_valids, b_sorted, b_perm, b_live,
                   post_scalars, W: int, out_cap: int):
        if c_pred is not None:
            pv, pm = c_pred.fn(p_arrays, p_valids, p_mask, p_scalars)[-1]
            p_mask = p_mask & pv.astype(jnp.bool_) & pm
        counts, starts, total = kernels.join_count_impl(
            p_arrays[lkey], p_valids[lkey], p_mask, b_sorted, b_live)
        owner, ridx, pair_valid = kernels.join_expand_impl(
            counts, starts, b_perm, W)
        j_arrays, j_valids = {}, {}
        for nm in probe_needs:
            j_arrays[nm] = jnp.take(p_arrays[nm], owner)
            j_valids[nm] = jnp.take(p_valids[nm], owner) & pair_valid
        for nm in build_needs:
            j_arrays[nm] = jnp.take(b_arrays[nm], ridx)
            j_valids[nm] = jnp.take(b_valids[nm], ridx) & pair_valid
        outs = c_post.fn(j_arrays, j_valids, pair_valid, post_scalars)
        mask = pair_valid
        if has_post_pred:
            qv, qm = outs[-1]
            mask = mask & qv.astype(jnp.bool_) & qm
            outs = outs[:-1]
        keys = tuple(v for v, _ in outs[:nk])
        kvalids = tuple(m for _, m in outs[:nk])
        vals = tuple(v for v, _ in outs[nk:])
        vvalids = tuple(m for _, m in outs[nk:])
        ok, okv, ov, ovv, g = kernels.grouped_agg_block_impl(
            keys, kvalids, vals, vvalids, mask, ops, out_cap)
        flat = list(ok) + list(okv) + list(ov) + list(ovv)
        meta["grouped_dtypes"] = [x.dtype for x in flat]
        head = jnp.zeros((out_cap,), jnp.int64) \
            .at[0].set(g.astype(jnp.int64)) \
            .at[1].set(total.astype(jnp.int64))
        return jnp.stack([head] + [_pack_i64(x) for x in flat])

    prog = FusedJoinAggProgram(
        jax.jit(run_packed, static_argnames=("W", "out_cap")),
        run_packed, c_pred, c_post, lkey, rkey, probe_needs, build_needs,
        nk, ops, has_post_pred, meta, fused_ops=fused_ops)
    try:
        need = set(probe_needs) | {lkey} \
            | set(c_pred.needs_cols if c_pred is not None else ())
        prog.in_np_dtypes = {
            nm: dcol.device_np_dtype(src_schema[nm].dtype) for nm in need}
        bneed = set(build_needs) | {rkey}
        prog.build_np_dtypes = {
            nm: dcol.device_np_dtype(build_schema[nm].dtype)
            for nm in bneed}
    except (ValueError, KeyError):
        prog.in_np_dtypes = None
        prog.build_np_dtypes = None
    _region_cache[key] = prog
    return prog


class InflightJoinAgg:
    """One in-flight join_agg region dispatch (+ dual-ladder state)."""

    __slots__ = ("prog", "dt", "build", "group_exprs", "key_fields",
                 "agg_fields", "W", "out_cap", "packed", "t0",
                 "submitted_s")

    def __init__(self, prog, dt, build, group_exprs, key_fields,
                 agg_fields, W, out_cap):
        import time as _time
        self.prog = prog
        self.dt = dt
        self.build = build
        self.group_exprs = group_exprs
        self.key_fields = key_fields
        self.agg_fields = agg_fields
        self.W = W
        self.out_cap = out_cap
        self.packed = None
        self.t0 = _time.perf_counter()
        self.submitted_s = 0.0


def _dispatch_join_agg(prog: FusedJoinAggProgram, dt: dcol.DeviceTable,
                       build: RegionBuild, W: int, out_cap: int):
    from ..analysis import retrace_sanitizer
    p_arrays = {n: col.data for n, col in dt.columns.items()}
    p_valids = {n: col.validity for n, col in dt.columns.items()}
    b_arrays = {n: col.data for n, col in build.dt.columns.items()}
    b_valids = {n: col.validity for n, col in build.dt.columns.items()}
    p_scalars = runtime._prep_scalars(prog.c_pred, dt) \
        if prog.c_pred is not None else ()
    post_scalars = _prep_scalars_joined(prog.c_post, dt, build.dt)
    with retrace_sanitizer.dispatch_scope(
            "region.join_agg",
            (id(prog), dt.capacity, build.dt.capacity, W, out_cap,
             tuple(s.shape for s in p_scalars),
             tuple(s.shape for s in post_scalars))):
        return prog.packed_fn(p_arrays, p_valids, dt.row_mask, p_scalars,
                              b_arrays, b_valids, build.sorted_key,
                              build.perm, build.live_count, post_scalars,
                              W=W, out_cap=out_cap)


def _prep_scalars_joined(c: compiler.Compiled, p_dt: dcol.DeviceTable,
                         b_dt: dcol.DeviceTable):
    """Scalar prep over the joined namespace: each spec's dictionary
    comes from whichever side encoded the column."""
    import pyarrow as pa
    scalars = []
    for spec in c.scalar_specs:
        src = p_dt.columns.get(spec.col) or b_dt.columns.get(spec.col)
        d = src.dictionary if src is not None else None
        if d is None:
            d = pa.array([], type=pa.large_string())
        scalars.append(jnp.asarray(spec.fn(d)))
    return tuple(scalars)


def submit_join_agg(prog: FusedJoinAggProgram, batch, build: RegionBuild,
                    group_exprs, agg_exprs, out_schema: Schema,
                    start_out_cap: int = _OUT_CAP0
                    ) -> Optional[InflightJoinAgg]:
    """Encode + async dispatch of one probe morsel; None → host
    fallback."""
    import time as _time
    need = list(dict.fromkeys(
        [prog.lkey] + list(prog.probe_needs)
        + list(prog.c_pred.needs_cols if prog.c_pred is not None else ())))
    for nm in need:
        if batch.get_column(nm).is_pyobject():
            return None
    try:
        dt = dcol.encode_batch(batch, need)
    except (ValueError, TypeError):
        return None
    key_fields = [out_schema[e.name()] for e in group_exprs]
    agg_fields = [out_schema[e.name()] for e in agg_exprs]
    # expected ≤1 build match per probe row (FK equi-join): start the pair
    # bucket at the probe capacity; the header's true total grows it
    W = dt.capacity
    tok = InflightJoinAgg(prog, dt, build, group_exprs, key_fields,
                          agg_fields, W,
                          min(dcol.bucket_capacity(max(start_out_cap,
                                                       _OUT_CAP0)),
                              dcol.bucket_capacity(W)))
    tok.packed = _dispatch_join_agg(prog, dt, build, tok.W, tok.out_cap)
    tok.submitted_s = _time.perf_counter() - tok.t0
    return tok


def drain_join_agg(tok: InflightJoinAgg):
    """Blocking drain: one packed fetch → partial-group RecordBatch,
    continuing the DUAL overflow ladder (pair width W, group bucket
    out_cap) read from the packed header. None → host fallback."""
    import time as _time

    from . import costmodel, pipeline
    prog = tok.prog
    t_drain0 = _time.perf_counter()
    while True:
        packed = np.asarray(pipeline.fetch_host(tok.packed))
        g = int(packed[0, 0])
        total = int(packed[0, 1])
        grown = False
        if total > tok.W:
            if total > _REGION_MAX_W:
                return None
            tok.W = dcol.bucket_capacity(total)
            grown = True
        if g > tok.out_cap:
            cap_limit = dcol.bucket_capacity(max(tok.W, tok.dt.capacity))
            if g > cap_limit:
                return None
            tok.out_cap = min(dcol.bucket_capacity(g), cap_limit)
            grown = True
        if grown:
            tok.packed = _dispatch_join_agg(prog, tok.dt, tok.build,
                                            tok.W, tok.out_cap)
            continue
        from ..recordbatch import RecordBatch
        dtypes = prog.meta["grouped_dtypes"]
        nk, nv = prog.nk, len(tok.agg_fields)
        rows = packed[1:]
        cols = []
        for i, (e, f) in enumerate(zip(tok.group_exprs, tok.key_fields)):
            kv = _unpack_i64(rows[i][:g], dtypes[i])
            km = _unpack_i64(rows[nk + i][:g],
                             dtypes[nk + i]).astype(np.bool_)
            dc = dcol.DeviceColumn(kv, km, f.dtype, None)
            cols.append(dcol.decode_column(f.name, dc, g))
        for i, f in enumerate(tok.agg_fields):
            vv = _unpack_i64(rows[2 * nk + i][:g], dtypes[2 * nk + i])
            vm = _unpack_i64(rows[2 * nk + nv + i][:g],
                             dtypes[2 * nk + nv + i]).astype(np.bool_)
            dc = dcol.DeviceColumn(vv, vm, f.dtype, None)
            cols.append(dcol.decode_column(f.name, dc, g))
        out = RecordBatch.from_series(cols)
        n_ops = max(len(prog.fused_ops), 3)
        secs = tok.submitted_s + (_time.perf_counter() - t_drain0)
        costmodel.ledger_record(
            "region", rows=tok.dt.row_count,
            nbytes=(1 + 2 * (nk + nv)) * 8 * tok.out_cap, seconds=secs,
            strategy="join_agg", fused_ops=n_ops,
            round_trips_saved=n_ops - 1,
            fusion_serial_seconds=costmodel.fusion_serial_estimate(
                tok.dt.row_count, n_ops))
        return out, g


def fused_region_programs() -> List[object]:
    """Every region program compiled so far — the AOT warm-up grid
    (device/warmup.py) iterates these alongside the fused-agg library."""
    return [p for p in _region_cache.values()
            if isinstance(p, (FusedRegionProgram, FusedJoinAggProgram))]
