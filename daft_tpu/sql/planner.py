"""Hand-written SQL frontend: tokenizer + recursive-descent parser → LogicalPlan.

Capability mirror of ``src/daft-sql`` (planner over sqlparser-rs;
``planner.rs``): SELECT with CTEs, derived tables, JOIN chains (ON/USING,
inner/left/right/full/cross/semi/anti), WHERE / GROUP BY / HAVING / ORDER BY /
LIMIT / OFFSET, DISTINCT, UNION [ALL], scalar + aggregate expressions (CASE,
CAST, BETWEEN, IN, LIKE, IS NULL, EXTRACT, INTERVAL, date literals), and a
function library mapped onto the expression DSL. No third-party SQL dependency
exists in this environment, so the parser is first-party.
"""

from __future__ import annotations

import datetime
import re
from typing import Dict, List, Optional, Tuple

from ..datatype import DataType, TimeUnit
from ..expressions import Expression, col, lit, coalesce
from ..expressions.expressions import list_
from ..logical.optimizer import substitute_columns

# ---------------------------------------------------------------------------
# tokenizer

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+|\d+(?:[eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><=|>=|<>|!=|\|\||::|[-+*/%(),.<>=])
""", re.VERBOSE)


class Tok:
    __slots__ = ("kind", "text")

    def __init__(self, kind: str, text: str):
        self.kind = kind
        self.text = text

    def __repr__(self):
        return f"{self.kind}:{self.text}"


def tokenize(s: str) -> List[Tok]:
    out = []
    i = 0
    while i < len(s):
        m = _TOKEN_RE.match(s, i)
        if m is None:
            raise ValueError(f"SQL tokenize error at {s[i:i+20]!r}")
        i = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        text = m.group()
        if kind == "ident":
            out.append(Tok("ident", text))
        elif kind == "qident":
            out.append(Tok("ident", text[1:-1].replace('""', '"')))
        elif kind == "str":
            out.append(Tok("str", text[1:-1].replace("''", "'")))
        else:
            out.append(Tok(kind, text))
    out.append(Tok("eof", ""))
    return out


_AGG_FUNCS = {"sum", "avg", "mean", "min", "max", "count", "count_distinct",
              "stddev", "stddev_pop", "var", "variance", "any_value",
              "approx_count_distinct", "list_agg", "string_agg", "skew"}


class Scope:
    """Name resolution: alias → {sql column name → actual frame column}."""

    def __init__(self):
        self.tables: Dict[str, Dict[str, str]] = {}
        self.order: List[str] = []

    def add(self, alias: str, columns: List[str],
            actual: Optional[Dict[str, str]] = None):
        self.tables[alias] = {c.lower(): (actual[c] if actual else c)
                              for c in columns}
        self.order.append(alias)

    def prefix_right(self, collided: List[str]):
        """After a join, right-side collided columns become right.<name>."""
        last = self.order[-1]
        m = self.tables[last]
        for sqlname, act in list(m.items()):
            if act in collided:
                m[sqlname] = "right." + act

    def resolve(self, name: str, alias: Optional[str] = None) -> str:
        if alias is not None:
            t = self.tables.get(alias.lower())
            if t is None or name.lower() not in t:
                raise ValueError(f"unknown column {alias}.{name}")
            return t[name.lower()]
        for a in self.order:
            if name.lower() in self.tables[a]:
                return self.tables[a][name.lower()]
        raise ValueError(f"unknown column {name}")

    def all_columns(self) -> List[str]:
        seen, out = set(), []
        for a in self.order:
            for act in self.tables[a].values():
                if act not in seen:
                    seen.add(act)
                    out.append(act)
        return out


class _SubCtx:
    """Per-subquery parse context: the enclosing scope for correlated name
    resolution plus what the unnesting rewrite needs (see
    ``logical/subquery.py``)."""

    __slots__ = ("outer_scope", "corr", "resid", "deferred_aggs",
                 "deferred_group_by", "value_names", "owned", "cte_depth")

    def __init__(self, outer_scope: Scope, cte_depth: int = 0):
        self.outer_scope = outer_scope
        self.corr = []            # [(inner_expr, outer_expr)]
        self.resid = []           # correlated NON-equality conjuncts
        #                           (outer_col markers intact)
        self.deferred_aggs = []   # select exprs when agg is deferred
        self.deferred_group_by = []  # the subquery's own GROUP BY keys
        self.value_names = []     # projected output names of the sub root
        self.owned = False        # claimed by the subquery's root SELECT
        self.cte_depth = cte_depth  # root select lives at this CTE depth


class SQLPlanner:
    def __init__(self, tables: Dict[str, "object"], session=None):
        self.tables = {k.lower(): v for k, v in tables.items()}
        self.session = session
        self.toks: List[Tok] = []
        self.i = 0
        self._sub_stack: List[_SubCtx] = []
        self._cur_ctes: Dict[str, "object"] = {}
        self._cte_depth = 0

    # -- public ------------------------------------------------------------
    def plan_query(self, query: str):
        self.toks = tokenize(query)
        self.i = 0
        df = self._query(dict(self.tables))
        self._expect_eof()
        return df

    def plan_statement(self, query: str):
        """Statement router (reference: ``src/daft-sql``'s statement layer
        + ``exec.rs``): DDL/DML — CREATE [TEMP] TABLE … AS, INSERT INTO,
        DROP TABLE, SHOW TABLES, DESCRIBE, USE — execute against the bound
        session; anything else plans as a query."""
        self.toks = tokenize(query)
        self.i = 0
        if self._peek_kw("CREATE"):
            return self._create_stmt()
        if self._peek_kw("INSERT", "INTO"):
            return self._insert_stmt()
        if self._peek_kw("DROP", "TABLE"):
            return self._drop_stmt()
        if self._peek_kw("SHOW", "TABLES"):
            return self._show_tables_stmt()
        if self._peek_kw("DESCRIBE"):
            return self._describe_stmt()
        if self._peek_kw("USE"):
            return self._use_stmt()
        return self.plan_query(query)

    # -- statements --------------------------------------------------------
    def _need_session(self, what: str):
        if self.session is None:
            raise ValueError(f"{what} needs a session (daft_tpu.Session)")
        return self.session

    def _ident_chain(self) -> List[str]:
        parts = [self._next().text]
        while self._kw("."):
            parts.append(self._next().text)
        return parts

    def _create_stmt(self):
        self._expect("CREATE")
        replace = self._kw("OR", "REPLACE")
        temp = self._kw("TEMP") or self._kw("TEMPORARY")
        self._expect("TABLE")
        if_not_exists = self._kw("IF", "NOT", "EXISTS")
        parts = self._ident_chain()
        self._expect("AS")
        rest = self.toks[self.i:]
        self.toks = rest
        self.i = 0
        df = self._query(dict(self.tables))
        self._expect_eof()
        sess = self._need_session("CREATE TABLE")
        from ..catalog import Identifier
        if temp:
            if len(parts) != 1:
                raise ValueError("temp table names are unqualified")
            # only the TEMP namespace matters here: temp tables shadow
            # catalog tables by design, so a catalog name never blocks one
            exists = parts[0] in sess._tables
            if exists and if_not_exists:
                return df  # no-op, existing table preserved
            if exists and not replace:
                raise ValueError(f"table {parts[0]!r} already exists")
            sess.create_temp_table(parts[0], df)
            return df
        # a leading part naming an attached catalog addresses that catalog
        # (same resolution as Session.get_table)
        if len(parts) > 1 and sess.has_catalog(parts[0]):
            target = sess.get_catalog(parts[0])
            ident = Identifier(*parts[1:])
        else:
            target = sess
            ident = Identifier(*parts)
        if if_not_exists:
            target.create_table_if_not_exists(ident, df)
        elif replace:
            try:
                target.drop_table(ident)
            except Exception:
                pass
            target.create_table(ident, df)
        else:
            target.create_table(ident, df)
        return df

    def _insert_stmt(self):
        self._expect("INSERT")
        self._expect("INTO")
        parts = self._ident_chain()
        mode = "append"
        if self._kw("OVERWRITE"):
            mode = "overwrite"
        rest = self.toks[self.i:]
        self.toks = rest
        self.i = 0
        df = self._query(dict(self.tables))
        self._expect_eof()
        sess = self._need_session("INSERT INTO")
        sess.get_table(".".join(parts)).write(df, mode=mode)
        return df

    def _drop_stmt(self):
        from ..catalog import NotFoundError
        self._expect("DROP")
        self._expect("TABLE")
        if_exists = self._kw("IF", "EXISTS")
        parts = self._ident_chain()
        self._expect_eof()
        sess = self._need_session("DROP TABLE")
        try:
            sess.drop_table(".".join(parts))
        except NotFoundError:
            # IF EXISTS only forgives absence — IO/permission failures
            # still surface
            if not if_exists:
                raise
        return None

    def _show_tables_stmt(self):
        import fnmatch

        from .. import dataframe as _df
        self._expect("SHOW")
        self._expect("TABLES")
        pattern = None
        if self._kw("LIKE"):
            # SQL LIKE wildcards → fnmatch (%→*, _→?)
            raw = self._next().text.strip("'\"")
            pattern = raw.replace("%", "*").replace("_", "?")
        self._expect_eof()
        sess = self._need_session("SHOW TABLES")
        names = [str(t) for t in sess.list_tables(None)]
        if pattern is not None:
            names = [n for n in names if fnmatch.fnmatchcase(n, pattern)]
        return _df.from_pydict({"table": names})

    def _describe_stmt(self):
        from .. import dataframe as _df
        self._expect("DESCRIBE")
        parts = self._ident_chain()
        self._expect_eof()
        sess = self._need_session("DESCRIBE")
        schema = sess.get_table(".".join(parts)).schema()
        return _df.from_pydict({
            "column": [f.name for f in schema],
            "type": [str(f.dtype) for f in schema]})

    def _use_stmt(self):
        self._expect("USE")
        parts = self._ident_chain()
        self._expect_eof()
        self._need_session("USE").use(".".join(parts))
        return None

    def plan_expression(self, text: str) -> Expression:
        self.toks = tokenize(text)
        self.i = 0
        e = self._expr(scope=None)
        self._expect_eof()
        return e

    # -- cursor ------------------------------------------------------------
    def _peek(self, k: int = 0) -> Tok:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def _next(self) -> Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def _kw(self, *words: str) -> bool:
        """Consume keyword/punctuation sequence if present (case-insensitive).
        String/quoted-identifier tokens never match keywords."""
        save = self.i
        for w in words:
            t = self._peek()
            if w.isalpha():
                ok = t.kind == "ident" and t.text.upper() == w
            else:
                ok = t.kind == "op" and t.text == w
            if ok:
                self.i += 1
            else:
                self.i = save
                return False
        return True

    def _peek_kw(self, *words: str) -> bool:
        save = self.i
        ok = self._kw(*words)
        self.i = save
        return ok

    def _expect(self, text: str):
        t = self._next()
        if t.text.upper() != text.upper():
            raise ValueError(f"expected {text!r}, got {t.text!r}")

    def _expect_eof(self):
        if self._peek().kind != "eof":
            raise ValueError(f"unexpected trailing SQL: {self._peek().text!r}")

    # -- query -------------------------------------------------------------
    def _query(self, ctes: Dict[str, "object"]):
        if self._kw("WITH"):
            while True:
                name = self._next().text
                self._expect("AS")
                self._expect("(")
                # a CTE body must not claim an enclosing subquery's context
                # (the subquery's ROOT select owns it) — see _select
                self._cte_depth += 1
                try:
                    sub = self._query(dict(ctes))
                finally:
                    self._cte_depth -= 1
                self._expect(")")
                ctes[name.lower()] = sub
                if not self._kw(","):
                    break
        left = self._select_operand(ctes)
        while self._peek_kw("UNION") or self._peek_kw("INTERSECT") \
                or self._peek_kw("EXCEPT"):
            if self._kw("UNION"):
                all_ = self._kw("ALL")
                right = self._positional(left, self._select_operand(ctes))
                left = left.union_all(right) if all_ else left.union(right)
            elif self._kw("INTERSECT"):
                right = self._positional(left, self._select_operand(ctes))
                left = left.intersect(right)
            else:
                self._kw("EXCEPT")
                right = self._positional(left, self._select_operand(ctes))
                left = left.except_distinct(right)
        return left

    @staticmethod
    def _positional(left, right):
        """SQL set operations match columns by POSITION; rename the right
        operand's columns to the left's so the engine's name-based concat
        applies (reference resolves set-op schemas positionally too)."""
        lc, rc = list(left.column_names), list(right.column_names)
        if len(lc) != len(rc):
            raise ValueError(
                f"set operation operands have different column counts: "
                f"{len(lc)} vs {len(rc)}")
        if lc != rc:
            right = right.select(*[col(r).alias(l)
                                   for l, r in zip(lc, rc)])
        return right

    def _select_operand(self, ctes):
        """One set-operation operand: a SELECT, or a parenthesized query
        — ``(SELECT …) EXCEPT (SELECT …)`` — which may itself hold set
        ops."""
        if self._peek().text == "(" and \
                self._peek(1).text.upper() in ("SELECT", "WITH", "("):
            self._kw("(")
            sub = self._query(dict(ctes))
            self._expect(")")
            return sub
        return self._select(ctes)

    def _select(self, ctes):
        from ..dataframe import DataFrame
        prev_ctes = self._cur_ctes
        self._cur_ctes = ctes
        # the first SELECT parsed under a fresh subquery context is that
        # subquery's root: correlation pairs and deferred aggregates attach
        # to it (nested derived tables/subqueries push their own contexts)
        sub_ctx = None
        if self._sub_stack and not self._sub_stack[-1].owned \
                and self._sub_stack[-1].cte_depth == self._cte_depth:
            sub_ctx = self._sub_stack[-1]
            sub_ctx.owned = True
        try:
            return self._select_inner(ctes, sub_ctx)
        finally:
            self._cur_ctes = prev_ctes

    def _select_inner(self, ctes, sub_ctx):
        from ..dataframe import DataFrame
        self._expect("SELECT")
        distinct = self._kw("DISTINCT")
        proj: List[Tuple[Optional[Expression], Optional[str]]] = []
        while True:
            if self._peek().text == "*":
                self._next()
                proj.append((None, "*"))
            elif self._peek().kind == "ident" and self._peek(1).text == "." \
                    and self._peek(2).text == "*":
                alias = self._next().text
                self._next()
                self._next()
                proj.append((None, f"{alias}.*"))
            else:
                e = None  # parsed after FROM for scope; remember token span
                start = self.i
                self._skip_expr()
                out_alias = None
                if self._kw("AS"):
                    out_alias = self._next().text
                elif self._peek().kind == "ident" and \
                        self._peek().text.upper() not in (
                            "FROM", "WHERE", "GROUP", "ORDER", "LIMIT",
                            "HAVING", "UNION", "INTERSECT", "EXCEPT",
                            "OFFSET"):
                    out_alias = self._next().text
                proj.append(((start, self.i - (1 if out_alias and
                                               not self._prev_was_as(start) else 0)),
                             out_alias))
            if not self._kw(","):
                break

        # FROM -----------------------------------------------------------
        scope = Scope()
        if self._kw("FROM"):
            df = self._table_expr(ctes, scope)
        else:
            df = DataFrame.__new__(DataFrame)  # dummy; no-FROM SELECT
            from ..dataframe import from_pydict
            df = from_pydict({"__dummy__": [0]})
            scope.add("__dummy__", ["__dummy__"])

        where = None
        if self._kw("WHERE"):
            where = self._expr(scope)
        group_by = []
        grouping_sets = None  # list of key-lists when ROLLUP/CUBE/SETS used
        if self._kw("GROUP"):
            self._expect("BY")
            items = []
            while True:
                if self._peek_kw("ROLLUP", "("):
                    self._kw("ROLLUP", "(")
                    items.append(("rollup", self._expr_list(scope)))
                    self._expect(")")
                elif self._peek_kw("CUBE", "("):
                    self._kw("CUBE", "(")
                    items.append(("cube", self._expr_list(scope)))
                    self._expect(")")
                elif self._peek_kw("GROUPING", "SETS", "("):
                    self._kw("GROUPING", "SETS", "(")
                    sets = []
                    while True:
                        if self._kw("("):
                            ks = [] if self._peek().text == ")" \
                                else self._expr_list(scope)
                            self._expect(")")
                            sets.append(ks)
                        else:
                            sets.append([self._expr(scope)])
                        if not self._kw(","):
                            break
                    self._expect(")")
                    items.append(("sets", sets))
                else:
                    items.append(("plain", [self._expr(scope)]))
                if not self._kw(","):
                    break
            group_by, grouping_sets = _expand_group_items(items)
        having = None
        if self._kw("HAVING"):
            having = self._expr(scope)
        order_by = []
        descs = []
        if self._kw("ORDER"):
            self._expect("BY")
            lenient = _LenientScope(scope)
            while True:
                order_by.append(self._expr(lenient))
                if self._kw("DESC"):
                    descs.append(True)
                else:
                    self._kw("ASC")
                    descs.append(False)
                if not self._kw(","):
                    break
        limit = None
        offset = 0
        if self._kw("LIMIT"):
            limit = int(self._next().text)
        if self._kw("OFFSET"):
            offset = int(self._next().text)

        # re-parse projection expressions with full scope ------------------
        exprs: List[Expression] = []
        bare_alias: dict = {}  # exprs index → deferred bare-name alias
        save = self.i
        for item, alias in proj:
            if item is None:
                if alias == "*":
                    exprs.extend(col(c) for c in scope.all_columns())
                else:
                    a = alias.split(".")[0]
                    for actual in scope.tables[a.lower()].values():
                        exprs.append(col(actual))
                continue
            start, end = item
            self.i = start
            e = self._expr(scope)
            if alias is None and self.i == end - 1 \
                    and self._peek().kind == "ident":
                # implicit alias (``SELECT x total``): _skip_expr ran to
                # the delimiter, so exactly one trailing bare ident inside
                # the recorded span is the AS-less output name
                alias = self._next().text
            if alias is None and e.op in ("col", "outer_col") \
                    and end - start == 3 \
                    and self.toks[start + 1].text == "." \
                    and e.params[0] != self.toks[end - 1].text:
                # SQL names an unaliased qualified reference by its BARE
                # column name (``SELECT t.customer_id`` → customer_id) —
                # self-join collision renames must not leak internal
                # ``right.x`` names into the output schema. DEFERRED:
                # applied below only when the bare name doesn't collide
                # with another SELECT item's output name (``SELECT a.x,
                # b.x FROM t a JOIN t b`` must keep planning as
                # x / right.x, not raise on two ``x`` outputs)
                bare_alias[len(exprs)] = self.toks[end - 1].text
            if alias is not None:
                e = e.alias(alias)
            exprs.append(e)
        self.i = save
        if bare_alias:
            names = [bare_alias.get(i, e.name())
                     for i, e in enumerate(exprs)]
            for i, nm in bare_alias.items():
                if names.count(nm) == 1:
                    exprs[i] = exprs[i].alias(nm)
        # ORDER BY <integer> is a SELECT-list ordinal (SQL standard), not
        # a constant sort key (which would be a silent no-op sort)
        for j, o in enumerate(order_by):
            u = o._unalias()
            if u.op == "lit" and type(u.params[0]) is int \
                    and 1 <= u.params[0] <= len(exprs):
                order_by[j] = col(exprs[u.params[0] - 1].name())

        # assemble plan ----------------------------------------------------
        from ..logical import subquery as subq
        if where is not None:
            df = self._apply_where(df, where, sub_ctx)
        agg_mode = bool(group_by) or any(_has_agg(e) for e in exprs) \
            or (having is not None and _has_agg(having))
        if having is not None and not agg_mode:
            # HAVING binds to a grouped query; without GROUP BY or any
            # aggregate, silently dropping it would return unfiltered rows
            raise NotImplementedError(
                "HAVING without GROUP BY or aggregates")
        if sub_ctx is not None:
            sub_ctx.value_names = [e.name() for e in exprs]
            if sub_ctx.corr and agg_mode:
                # correlated aggregating subquery: the unnesting rewrite
                # re-aggregates grouped by the correlation keys ∪ the
                # subquery's own GROUP BY keys — defer both. Clauses that
                # would apply AFTER the aggregate cannot be deferred
                # faithfully: refuse rather than silently drop.
                if group_by and grouping_sets is not None:
                    raise NotImplementedError(
                        "correlated subquery with ROLLUP/GROUPING SETS")
                if having is not None or distinct or order_by \
                        or limit is not None or offset:
                    raise NotImplementedError(
                        "correlated aggregating subquery with "
                        "HAVING/DISTINCT/ORDER BY/LIMIT")
                sub_ctx.deferred_aggs = exprs
                sub_ctx.deferred_group_by = list(group_by)
                return df
            if (sub_ctx.corr or sub_ctx.resid) and not agg_mode:
                # the correlation keys AND any inner columns the residual
                # predicates reference must survive the projection for the
                # unnest join (e.g. EXISTS(SELECT 1 FROM t WHERE k = outer
                # AND t.wh <> outer.wh) needs t.wh)
                names = {e.name() for e in exprs}
                needed = set()
                for inner, _ in sub_ctx.corr:
                    needed |= subq.free_columns(inner)
                for r in sub_ctx.resid:
                    needed |= subq.free_columns(r)  # col() refs only —
                    # outer_col markers are a distinct op, not collected
                avail_here = set(df.column_names)
                for c in sorted(needed):
                    if c not in names and c in avail_here:
                        exprs.append(col(c))
                        names.add(c)
        if agg_mode:
            # select-list scalar subqueries in an aggregating query attach
            # POST-aggregation (they are uncorrelated 1-row values; a
            # correlated one would need the pre-agg frame — unsupported).
            # Applies to plain GROUP BY and ROLLUP/GROUPING SETS alike.
            sub_exprs = [e for e in exprs if subq.contains_subquery(e)]
            for e in sub_exprs:
                if _has_agg(e):
                    raise NotImplementedError(
                        "select item mixing aggregates and scalar "
                        "subqueries")
            placeholders = {id(e): lit(None).alias(e.name())
                            for e in sub_exprs}
            lower_exprs = [placeholders.get(id(e), e) for e in exprs]
            if grouping_sets is not None:
                df = self._lower_grouping_sets(df, group_by, grouping_sets,
                                               lower_exprs, having)
            else:
                df = self._lower_aggregate(df, group_by, lower_exprs,
                                           having)
            if sub_exprs:
                df = self._attach_select_subqueries(
                    df, exprs, only_ids={id(e) for e in sub_exprs})
        else:
            if any(subq.contains_subquery(e) for e in exprs):
                df, exprs = self._inline_select_subqueries(df, exprs)
            # hidden sort keys: SQL allows ordering by non-projected inputs
            hidden = []
            if order_by:
                out_names = {e.name() for e in exprs}
                for j, o in enumerate(order_by):
                    bound = _rebind_order(o, exprs)
                    if bound.op == "col" and bound.params[0] in out_names:
                        order_by[j] = bound
                    elif not (o.op == "col" and o.params[0] in out_names):
                        h = o.alias(f"__ord{j}__")
                        hidden.append(h)
                        order_by[j] = col(h.name())
            df = df.select(*(exprs + hidden))
            if distinct and not hidden:
                df = df.distinct()
            if order_by:
                df = df.sort(order_by, desc=descs)
            if hidden:
                df = df.select(*[col(e.name()) for e in exprs])
                if distinct:
                    df = df.distinct()
            order_by = []
        if distinct and (agg_mode):
            df = df.distinct()
        if order_by:
            # order keys may reference output aliases
            df = df.sort([_rebind_order(o, exprs) for o in order_by],
                         desc=descs)
        if limit is not None:
            df = df.limit(limit, offset)
        elif offset:
            df = df.offset(offset)
        return df

    def _attach_select_subqueries(self, df, exprs, only_ids):
        """Post-aggregation realization of select-list scalar subqueries:
        the aggregate was lowered with NULL placeholders for these items;
        attach each subquery's 1-row value (cross join) and re-project the
        output in order (reference: subqueries are plain Expr variants
        usable anywhere, ``src/daft-dsl/src/expr/mod.rs:213-292``)."""
        from ..logical import subquery as subq
        final = []
        for e in exprs:
            if id(e) in only_ids:
                name = e.name()
                df, e = subq.realize_scalars(df, e)
                final.append(e._unalias().alias(name))
            else:
                final.append(col(e.name()))
        return df.select(*final)

    def _inline_select_subqueries(self, df, exprs):
        """Pre-projection realization for non-aggregating selects:
        supports correlated subqueries too (the outer frame is intact)."""
        from ..logical import subquery as subq
        out = []
        for e in exprs:
            if subq.contains_subquery(e):
                name = e.name()
                df, e = subq.realize_scalars(df, e)
                e = e._unalias().alias(name)
            out.append(e)
        return df, out

    def _expr_list(self, scope) -> List[Expression]:
        out = [self._expr(scope)]
        while self._kw(","):
            out.append(self._expr(scope))
        return out

    def _pull_window_aggs(self, exprs):
        """Decompose select items that mix GROUP BY aggregates with OVER()
        windows — ``SUM(SUM(x)) OVER (…)``, ``RANK() OVER (ORDER BY
        SUM(x))``, ``SUM(x)*100/SUM(SUM(x)) OVER (PARTITION BY c)`` —
        into hidden aggregate outputs plus a post-aggregation expression
        that references them. Returns (new exprs, hidden agg exprs).
        Reference treats windows-over-aggregates the same way: the inner
        aggregate runs at the groupby, the window over the grouped frame
        (``src/daft-sql/src/modules/window.rs``)."""
        hidden: List[Expression] = []

        def mk_hidden(a: Expression) -> Expression:
            for h in hidden:
                if h._unalias().structurally_eq(a):
                    return col(h.name())
            nm = f"__wagg{len(hidden)}__"
            hidden.append(a.alias(nm))
            return col(nm)

        def pull_below(e):
            if not isinstance(e, Expression):
                return e
            if e.op.startswith("agg."):
                return mk_hidden(e)
            if not e.args:
                return e
            return e.with_children([pull_below(a) for a in e.args])

        def fix(e: Expression) -> Expression:
            if e.op == "window":
                inner = e.args[0]
                # the window's own function node stays (it computes over
                # the grouped frame); aggregates in its ARGUMENTS ran at
                # the groupby and become hidden columns
                if inner.args:
                    inner = inner.with_children(
                        [pull_below(a) for a in inner.args])
                spec = e.params[0]._copy()
                spec._partition_by = [pull_below(p)
                                      for p in spec._partition_by]
                spec._order_by = [pull_below(o) for o in spec._order_by]
                return Expression("window", (inner,), (spec,))
            if e.op.startswith("agg."):
                return mk_hidden(e)
            if not e.args:
                return e
            return e.with_children([fix(a) for a in e.args])

        out = [fix(e) if _contains_window(e) else e for e in exprs]
        return out, hidden

    def _lower_aggregate(self, df, gb_keys, exprs, having):
        """GROUP BY lowering for ONE grouping-key set: groupby + aggregate
        + HAVING filter + output projection (group keys by name, aggregates
        by alias, residual expressions — literals from ROLLUP null-fill or
        expressions over key columns — evaluated over the grouped frame).

        A HAVING with subqueries (TPC-H Q11's ``HAVING SUM(…) > (SELECT
        …)``) splits: its aggregate subtrees become hidden agg outputs and
        the residual predicate — subqueries included — applies as a WHERE
        over the grouped frame via the unnest machinery."""
        from ..logical import subquery as subq
        exprs, wagg_hidden = self._pull_window_aggs(exprs)
        agg_exprs = [e for e in exprs if _has_agg(e)] + wagg_hidden
        having_resid = None
        if having is not None:
            if subq.contains_subquery(having):
                hidden_aggs: List[Expression] = []

                def pull_aggs(e):
                    if e.op.startswith("agg."):
                        nm = f"__hv{len(hidden_aggs)}__"
                        hidden_aggs.append(e.alias(nm))
                        return col(nm)
                    if not e.args:
                        return e
                    return e.with_children([pull_aggs(a) for a in e.args])

                having_resid = pull_aggs(having)
                agg_exprs = agg_exprs + hidden_aggs
            else:
                agg_exprs = agg_exprs + [having.alias("__having__")]
        gdf = df.groupby(*gb_keys).agg(*agg_exprs) if gb_keys \
            else df.agg(*agg_exprs)
        if having_resid is not None:
            gdf = subq.apply_where(gdf, having_resid)
        elif having is not None:
            gdf = gdf.where(col("__having__"))
        sel = []
        for e in exprs:
            if _has_agg(e):
                sel.append(col(e.name()))
                continue
            inner = e._unalias()
            matched = None
            for g in gb_keys:
                if inner.structurally_eq(g) or e.structurally_eq(g):
                    matched = g.name()
                    break
            if matched is not None:
                sel.append(col(matched).alias(e.name())
                           if matched != e.name() else col(matched))
            elif inner.op == "col" and inner.params[0] in \
                    [g.name() for g in gb_keys]:
                sel.append(e)  # references an aliased group key by name
            else:
                # literal (ROLLUP null-fill) or expression over group-key
                # columns: evaluate against the grouped frame
                sel.append(e)
        return gdf.select(*sel)

    def _lower_grouping_sets(self, df, all_keys, sets, exprs, having):
        """ROLLUP / CUBE / GROUPING SETS → union of per-set aggregates
        (reference: planner.rs:390-401 lowers ROLLUP the same way). Keys
        absent from a set surface as typed NULLs — SQL's super-aggregate
        rows — and ``GROUPING(key)`` resolves to a literal 0/1 per branch,
        composing with any downstream expression for free.

        Window items (TPC-DS Q70/Q86's ``RANK() OVER (PARTITION BY
        GROUPING(a)+GROUPING(b) …)``) must rank over the UNION of
        branches, so each window's inputs (aggregates, grouping literals,
        spec expressions) are computed per branch as hidden columns and
        the window itself evaluates after the union."""
        if any(_contains_window(e) for e in exprs):
            return self._lower_grouping_sets_windows(df, all_keys, sets,
                                                     exprs, having)
        schema = df.schema()
        frames = []
        for S in sets:
            present = list(S)
            exprs_b = [self._subst_rollup(e, all_keys, present, schema)
                       for e in exprs]
            having_b = self._subst_rollup(having, all_keys, present,
                                          schema) if having is not None \
                else None
            frames.append(self._lower_aggregate(df, list(S), exprs_b,
                                                having_b))
        out = frames[0]
        for f in frames[1:]:
            out = out.union_all_by_name(f)
        return out

    def _lower_grouping_sets_windows(self, df, all_keys, sets, exprs,
                                     having):
        """Grouping-sets lowering when the select list holds window items:
        1. pull aggregates out of window nodes (hidden agg columns),
        2. extract each window's spec/argument expressions into hidden
           per-branch projections (GROUPING() → per-branch literal there),
        3. per-branch aggregate over [non-window items + hidden columns],
        4. union branches, evaluate the rebuilt windows, project."""
        exprs, wagg_hidden = self._pull_window_aggs(exprs)
        subs: List[Expression] = []
        spec_cols: set = set()  # plain columns referenced only in specs

        def mk_sub(e: Expression) -> Expression:
            if e.op == "col":
                spec_cols.add(e.params[0])
                return e  # already a frame column (hidden agg or key)
            for h in subs:
                if h._unalias().structurally_eq(e):
                    return col(h.name())
            nm = f"__wsub{len(subs)}__"
            subs.append(e.alias(nm))
            return col(nm)

        def extract(e: Expression) -> Expression:
            if e.op == "window":
                inner = e.args[0]
                if inner.args:
                    inner = inner.with_children(
                        [mk_sub(a) for a in inner.args])
                spec = e.params[0]._copy()
                spec._partition_by = [mk_sub(p)
                                      for p in spec._partition_by]
                spec._order_by = [mk_sub(o) for o in spec._order_by]
                return Expression("window", (inner,), (spec,))
            if not e.args:
                return e
            return e.with_children([extract(a) for a in e.args])

        final: List[Expression] = []       # post-union projection
        branch_items: List[Expression] = []  # per-branch select items
        for e in exprs:
            if _contains_window(e):
                final.append(extract(e)._unalias().alias(e.name()))
            else:
                branch_items.append(e)
                final.append(col(e.name()))
        # window items may also reference plain columns (keys, hidden agg
        # outputs) — ensure every free column of the rebuilt windows is in
        # the branch output
        have = {e.name() for e in branch_items} | \
               {e.name() for e in wagg_hidden} | \
               {e.name() for e in subs}
        need = set(spec_cols)  # Expression.column_names() walks args,
        for e in final:        # not the window spec stored in params
            need |= set(e.column_names())
        for c in sorted(need - have):
            branch_items.append(col(c))
        branch_items = branch_items + wagg_hidden + subs

        schema = df.schema()
        frames = []
        for S in sets:
            present = list(S)
            exprs_b = [self._subst_rollup(e, all_keys, present, schema)
                       for e in branch_items]
            having_b = self._subst_rollup(having, all_keys, present,
                                          schema) if having is not None \
                else None
            frames.append(self._lower_aggregate(df, list(S), exprs_b,
                                                having_b))
        out = frames[0]
        for f in frames[1:]:
            out = out.union_all_by_name(f)
        return out.select(*final)

    def _subst_rollup(self, e, all_keys, present, schema):
        """Per-branch rewrite: GROUPING(k) → 0/1 literal; references to
        keys OUTSIDE this grouping set → NULL cast to the key's type.

        The NULL substitution applies only to PROJECTED key references —
        never inside aggregate arguments: SQL's super-aggregate row
        computes ``count(a)`` over the real rows (nulling there returned
        count=0 on the grand total)."""
        if e.op == "sql.grouping":
            k = e.args[0]._unalias()
            is_present = any(k.structurally_eq(p._unalias())
                             for p in present)
            return lit(0 if is_present else 1)
        if e.op.startswith("agg."):
            return e
        u = e._unalias()
        if any(u.structurally_eq(k._unalias()) for k in all_keys):
            if not any(u.structurally_eq(p._unalias()) for p in present):
                dtype = u.to_field(schema).dtype
                return lit(None).cast(dtype).alias(e.name())
            return e
        if not e.args:
            return e
        return e.with_children([
            self._subst_rollup(a, all_keys, present, schema)
            for a in e.args])

    def _apply_where(self, df, where, sub_ctx):
        """Apply a WHERE clause: realize subquery nodes via unnest joins,
        and — inside a subquery — lift equality conjuncts that reference
        enclosing-scope columns into the context's correlation keys."""
        from ..logical import subquery as subq
        if sub_ctx is None and not subq.contains_subquery(where):
            return df.where(where)
        avail = set(df.column_names)

        def has_outer(e) -> bool:
            return e.op == "outer_col" or any(has_outer(a) for a in e.args)

        def unmark(e):
            """outer_col marker → plain col (for exprs that will evaluate
            against the ENCLOSING frame as join keys)."""
            if e.op == "outer_col":
                return col(e.params[0])
            if not e.args:
                return e
            return e.with_children([unmark(a) for a in e.args])

        plain = []
        for conj in subq.split_conjuncts(where):
            free = subq.free_columns(conj)
            outer = has_outer(conj)
            if not outer and (free <= avail or sub_ctx is None):
                plain.append(conj)
                continue
            u = conj._unalias()
            if sub_ctx is not None and outer \
                    and not subq.contains_subquery(u):
                if u.op == "eq":
                    a, b = u.args
                    lifted = False
                    for inner, outer_e in ((a, b), (b, a)):
                        if has_outer(inner):
                            continue
                        if subq.free_columns(inner) <= avail \
                                and has_outer(outer_e) \
                                and not subq.free_columns(outer_e):
                            sub_ctx.corr.append((inner, unmark(outer_e)))
                            lifted = True
                            break
                    if lifted:
                        continue
                # non-equality correlation (e.g. EXISTS … AND inner.wh <>
                # outer.wh, TPC-DS Q16/Q94): kept as a residual conjunct,
                # applied by the rowid-join rewrite in logical/subquery.py
                if subq.free_columns(conj) <= avail:
                    sub_ctx.resid.append(conj)
                    continue
            raise NotImplementedError(
                f"correlated predicate {conj!r}: equality correlation or "
                "single-level non-equality residuals (no nested subquery) "
                "are supported")
        if not plain:
            return df
        return subq.apply_where(df, subq.and_all(plain))

    def _parse_subquery(self, scope):
        """Parse ``(SELECT …)`` appearing as an expression operand; `scope`
        is the enclosing query's scope (for correlated name fallback)."""
        from ..logical import subquery as subq
        ctx = _SubCtx(scope if scope is not None else Scope(),
                      self._cte_depth)
        self._sub_stack.append(ctx)
        try:
            df = self._query(dict(self._cur_ctes))
        finally:
            self._sub_stack.pop()
        return subq.SubqueryInfo(
            df, ctx.corr, ctx.deferred_aggs,
            ctx.value_names if ctx.value_names else list(df.column_names),
            resid=ctx.resid, deferred_group_by=ctx.deferred_group_by)

    def _resolve_col(self, scope, name, alias=None) -> Expression:
        """Scope resolution with correlated fallback: a name unknown to the
        current scope may belong to an enclosing query's scope when we are
        inside a subquery. Outer references come back as marked
        ``outer_col`` nodes — the actual name alone cannot distinguish
        them when inner and outer tables share column names (e.g.
        ``item j`` correlated with outer ``item i`` on i_category)."""
        try:
            return col(scope.resolve(name, alias))
        except ValueError:
            for ctx in reversed(self._sub_stack):
                try:
                    actual = ctx.outer_scope.resolve(name, alias)
                except ValueError:
                    continue
                return Expression("outer_col", (), (actual,))
            raise

    def _prev_was_as(self, start: int) -> bool:
        return False

    def _skip_expr(self):
        """Skip over one projection expression (balanced parens) without
        resolving names — it is re-parsed once the FROM scope is known."""
        depth = 0
        while True:
            t = self._peek()
            if t.kind == "eof":
                return
            up = t.text.upper()
            if depth == 0 and (t.text == "," or up in (
                    "FROM", "WHERE", "GROUP", "ORDER", "LIMIT", "HAVING",
                    "UNION", "INTERSECT", "EXCEPT", "OFFSET")):
                return
            if depth == 0 and t.kind == "ident" and up == "AS":
                return
            if depth == 0 and t.kind == "ident" and self._peek(1).kind == "eof":
                self._next()
                return
            if t.text == "(":
                depth += 1
            elif t.text == ")":
                if depth == 0:
                    return
                depth -= 1
            self._next()

    # -- FROM clause -------------------------------------------------------
    def _table_expr(self, ctes, scope: Scope):
        df = self._table_factor(ctes, scope)
        while True:
            how = None
            if self._kw("CROSS", "JOIN"):
                how = "cross"
            elif self._kw("INNER", "JOIN") or self._peek_kw("JOIN"):
                self._kw("JOIN")
                how = "inner"
            elif self._kw("LEFT", "OUTER", "JOIN") or self._kw("LEFT", "JOIN"):
                how = "left"
            elif self._kw("RIGHT", "OUTER", "JOIN") or self._kw("RIGHT", "JOIN"):
                how = "right"
            elif self._kw("FULL", "OUTER", "JOIN") or self._kw("FULL", "JOIN"):
                how = "outer"
            elif self._kw("LEFT", "SEMI", "JOIN") or self._kw("SEMI", "JOIN"):
                how = "semi"
            elif self._kw("LEFT", "ANTI", "JOIN") or self._kw("ANTI", "JOIN"):
                how = "anti"
            elif self._kw(","):
                how = "cross"
            else:
                break
            right_scope = Scope()
            rdf = self._table_factor(ctes, right_scope)
            # rename colliding right columns BEFORE the ON condition
            # parses, so every later resolution sees final actual names
            # (self-join chains of any depth stay unambiguous)
            rdf, rename = self._rename_collisions(rdf, scope, right_scope)
            if how == "cross" and not self._peek_kw("ON"):
                df = self._merge_join(df, rdf, scope, right_scope, "cross",
                                      [], [], None, rename)
                continue
            if self._kw("USING"):
                self._expect("(")
                cols_u = []
                while True:
                    cols_u.append(self._next().text)
                    if not self._kw(","):
                        break
                self._expect(")")
                lo = [col(scope.resolve(c)) for c in cols_u]
                ro = [col(right_scope.resolve(c)) for c in cols_u]
                df = self._merge_join(df, rdf, scope, right_scope, how, lo,
                                      ro, None, rename, using=True)
                continue
            self._expect("ON")
            cond = self._expr_joined(scope, right_scope)
            lo, ro, residual = _split_join_condition(cond, scope, right_scope)
            df = self._merge_join(df, rdf, scope, right_scope,
                                  how if how != "cross" else "inner",
                                  lo, ro, residual, rename)
        return df

    def _rename_collisions(self, rdf, scope: Scope, right_scope: Scope):
        """Alias right-side columns that collide with the accumulated left
        scope to unique ``right[N].<name>`` actuals, updating the right
        scope in place. Keeps every plan's column names globally distinct
        (the optimizer's join rules rely on that) and makes self-join
        chains of any depth unambiguous."""
        lcols = set(scope.all_columns())

        def uniq(c: str) -> str:
            base = "right." + c
            n = 2
            while base in lcols:
                base = f"right{n}.{c}"
                n += 1
            return base

        rename = {c: uniq(c) for c in right_scope.all_columns()
                  if c in lcols}
        if rename:
            rdf = rdf.select(*[col(c).alias(rename.get(c, c))
                               for c in rdf.column_names])
            for alias in right_scope.order:
                right_scope.tables[alias] = {
                    sql: rename.get(act, act)
                    for sql, act in right_scope.tables[alias].items()}
        return rdf, rename

    def _merge_join(self, df, rdf, scope: Scope, right_scope: Scope, how,
                    lo, ro, residual, rename=None, using=False):
        """Join pre-renamed sides (see ``_rename_collisions``); the scope
        maps SQL names to the renamed actuals. Same-SQL-named equi keys
        resolve to the left copy (SQL's merged-key behavior)."""
        unrename = {v: k for k, v in (rename or {}).items()}
        ro_names = [e.name() for e in ro]
        lo_names = [e.name() for e in lo]
        out = None
        if residual is not None and how in ("left", "right", "outer"):
            # an outer join's ON residual filters the MATCH, not the rows:
            # a side-local residual pre-filters that side (equivalent);
            # one touching the preserved side (or both) needs true
            # theta-join semantics — lowered via row identity below
            resid_cols = set(residual.column_names())
            if how == "left" and resid_cols <= set(rdf.column_names):
                rdf = rdf.where(residual)
                residual = None
            elif how == "right" and resid_cols <= set(df.column_names):
                df = df.where(residual)
                residual = None
            else:
                out = self._theta_outer_join(df, rdf, lo, ro, residual,
                                             how)
                residual = None
        if out is None and how == "outer" and not using:
            # the DataFrame tier follows the reference and COALESCES outer
            # join keys; SQL's ON-join semantics keep both sides (a
            # right-only row has NULL left keys — TPC-DS Q97's channel
            # buckets depend on it), so SQL full-outer ON-joins take the
            # row-identity lowering. USING keeps the coalesce — that IS
            # its required semantics (COALESCE(l.k, r.k) as one column).
            out = self._theta_outer_join(df, rdf, lo, ro, None, how)
        theta = out is not None
        if theta:
            pass
        elif how == "cross":
            out = df.join(rdf, how="cross")
        else:
            out = df.join(rdf, left_on=lo, right_on=ro, how=how)
        for alias in right_scope.order:
            m = {}
            for sqlname, act in right_scope.tables[alias].items():
                if how in ("semi", "anti"):
                    continue
                # theta lowering keeps BOTH key copies with exact SQL
                # semantics (each side's copy is NULL on the other side's
                # missing piece) — the merged-key remap would resolve the
                # preserved side's key to a NULL left copy
                if act in ro_names and how not in ("outer",) and not theta:
                    ki = ro_names.index(act)
                    orig = unrename.get(act, act)
                    if ki < len(lo_names) and lo_names[ki] == orig:
                        m[sqlname] = lo_names[ki]  # merged key: left copy
                        continue
                m[sqlname] = act
            scope.tables[alias] = m
            scope.order.append(alias)
        if residual is not None:
            out = out.where(residual)
        return out

    def _theta_outer_join(self, df, rdf, lo, ro, residual, how):
        """LEFT/RIGHT/FULL OUTER join whose ON residual touches the
        preserved side (or both sides) — true theta-join semantics via row
        identity: the match set is the inner equi-join filtered by the
        residual; preserved rows with no surviving match re-enter with
        NULLs on the other side. The reference covers these through
        plan-level join predicates
        (``src/daft-logical-plan/src/optimization/rules/`` — the
        EliminateCrossJoin / join-predicate push family)."""
        from ..logical.subquery import _uid
        left_cols = list(df.column_names)
        right_cols = list(rdf.column_names)
        lrid = f"__thrid{next(_uid)}__"
        rrid = f"__thrid{next(_uid)}__"
        tl = df.add_monotonically_increasing_id(lrid)
        tr = rdf.add_monotonically_increasing_id(rrid)
        if lo:
            inner = tl.join(tr, left_on=lo, right_on=ro, how="inner")
        else:
            inner = tl.join(tr, how="cross")
        if residual is not None:
            inner = inner.where(residual)
        lsch, rsch = df.schema(), rdf.schema()
        both = [col(c) for c in left_cols + right_cols]
        pieces = [inner.select(*both)]
        if how in ("left", "outer"):
            missing = tl.join(inner.select(col(lrid)).distinct(),
                              left_on=[col(lrid)], right_on=[col(lrid)],
                              how="anti")
            pieces.append(missing.select(
                *([col(c) for c in left_cols]
                  + [lit(None).cast(rsch[c].dtype).alias(c)
                     for c in right_cols])))
        if how in ("right", "outer"):
            missing = tr.join(inner.select(col(rrid)).distinct(),
                              left_on=[col(rrid)], right_on=[col(rrid)],
                              how="anti")
            pieces.append(missing.select(
                *([lit(None).cast(lsch[c].dtype).alias(c)
                   for c in left_cols]
                  + [col(c) for c in right_cols])))
        out = pieces[0]
        for p in pieces[1:]:
            out = out.concat(p)
        return out

    def _table_factor(self, ctes, scope: Scope):
        if self._kw("("):
            sub = self._query(dict(ctes))
            self._expect(")")
            alias = None
            if self._kw("AS"):
                alias = self._next().text
            elif self._peek().kind == "ident" and \
                    self._peek().text.upper() not in _CLAUSE_WORDS:
                alias = self._next().text
            alias = alias or f"__subq{len(scope.order)}__"
            scope.add(alias, sub.column_names)
            return sub
        name = self._next().text
        # qualified names: cat.ns.table → single dotted lookup key
        while self._peek().text == "." and self.toks[self.i + 1].kind == "ident":
            self._next()
            name += "." + self._next().text
        # table functions: read_parquet('...') etc.
        if self._peek().text == "(" and name.lower() in (
                "read_parquet", "read_csv", "read_json"):
            self._next()
            path = self._next().text
            self._expect(")")
            import daft_tpu as _dt
            df = getattr(_dt, name.lower())(path)
        else:
            key = name.lower()
            df = ctes[key] if key in ctes else self.tables.get(key)
            if df is None and self.session is not None:
                from ..catalog import NotFoundError
                for candidate in (name, key):
                    try:
                        df = self.session.get_table(candidate).read()
                        break
                    except NotFoundError:
                        pass
            if df is None:
                raise ValueError(f"unknown table {name!r}")
            name = name.rsplit(".", 1)[-1]
        alias = None
        if self._kw("AS"):
            alias = self._next().text
        elif self._peek().kind == "ident" and \
                self._peek().text.upper() not in _CLAUSE_WORDS:
            alias = self._next().text
        scope.add((alias or name), df.column_names)
        return df

    # -- expressions -------------------------------------------------------
    def _expr_joined(self, left_scope: Scope, right_scope: Scope) -> Expression:
        merged = Scope()
        merged.tables = {**right_scope.tables, **left_scope.tables}
        merged.order = left_scope.order + right_scope.order
        return self._expr(merged)

    def _expr(self, scope: Optional[Scope]) -> Expression:
        return self._or_expr(scope)

    def _or_expr(self, scope) -> Expression:
        e = self._and_expr(scope)
        while self._kw("OR"):
            e = e | self._and_expr(scope)
        return e

    def _and_expr(self, scope) -> Expression:
        e = self._not_expr(scope)
        while self._kw("AND"):
            e = e & self._not_expr(scope)
        return e

    def _not_expr(self, scope) -> Expression:
        if self._kw("NOT"):
            return ~self._not_expr(scope)
        return self._cmp_expr(scope)

    def _cmp_expr(self, scope) -> Expression:
        e = self._add_expr(scope)
        while True:
            t = self._peek()
            if t.text in ("=", "<", ">", "<=", ">=", "<>", "!="):
                self._next()
                r = self._add_expr(scope)
                e = {"=": e == r, "<": e < r, ">": e > r, "<=": e <= r,
                     ">=": e >= r, "<>": e != r, "!=": e != r}[t.text]
                continue
            neg = False
            save = self.i
            if self._kw("NOT"):
                neg = True
            if self._kw("BETWEEN"):
                lo = self._add_expr(scope)
                self._expect("AND")
                hi = self._add_expr(scope)
                b = e.between(lo, hi)
                e = ~b if neg else b
                continue
            if self._kw("IN"):
                self._expect("(")
                if self._peek_kw("SELECT") or self._peek_kw("WITH"):
                    from ..logical import subquery as subq
                    info = self._parse_subquery(scope)
                    self._expect(")")
                    b = subq.in_expr(e, info)
                    e = ~b if neg else b
                    continue
                items = []
                while True:
                    items.append(self._expr(scope))
                    if not self._kw(","):
                        break
                self._expect(")")
                b = e.is_in([i.params[0] if i.op == "lit" else i
                             for i in items])
                e = ~b if neg else b
                continue
            if self._kw("LIKE"):
                pat = self._next().text
                rx = "^" + re.escape(pat).replace("%", ".*").replace("_", ".") \
                    + "$"
                b = e.str.match(rx)
                e = ~b if neg else b
                continue
            if self._kw("IS"):
                isnot = self._kw("NOT")
                self._expect("NULL")
                e = e.not_null() if isnot else e.is_null()
                continue
            if neg:
                self.i = save
            break
        return e

    def _add_expr(self, scope) -> Expression:
        e = self._mul_expr(scope)
        while True:
            t = self._peek().text
            if t == "+":
                self._next()
                e = e + self._mul_expr(scope)
            elif t == "-":
                self._next()
                e = e - self._mul_expr(scope)
            elif t == "||":
                self._next()
                e = e.str.concat(self._mul_expr(scope))
            else:
                return e

    def _mul_expr(self, scope) -> Expression:
        e = self._unary_expr(scope)
        while True:
            t = self._peek().text
            if t == "*":
                self._next()
                e = e * self._unary_expr(scope)
            elif t == "/":
                self._next()
                e = e / self._unary_expr(scope)
            elif t == "%":
                self._next()
                e = e % self._unary_expr(scope)
            else:
                return e

    def _unary_expr(self, scope) -> Expression:
        if self._peek().text == "-":
            self._next()
            return -self._unary_expr(scope)
        if self._peek().text == "+":
            self._next()
            return self._unary_expr(scope)
        e = self._primary(scope)
        while self._peek().text == "::":
            self._next()
            tname = self._next().text
            e = e.cast(_sql_type(tname, self))
        return e

    def _primary(self, scope) -> Expression:
        t = self._next()
        if t.text == "(":
            if self._peek_kw("SELECT") or self._peek_kw("WITH"):
                from ..logical import subquery as subq
                info = self._parse_subquery(scope)
                self._expect(")")
                return subq.scalar_expr(info)
            e = self._expr(scope)
            self._expect(")")
            return e
        if t.kind == "num":
            txt = t.text
            return lit(float(txt)) if ("." in txt or "e" in txt.lower()) \
                else lit(int(txt))
        if t.kind == "str":
            return lit(t.text)
        if t.kind != "ident":
            raise ValueError(f"unexpected token {t.text!r} in expression")
        up = t.text.upper()
        if up == "NULL":
            return lit(None)
        if up == "TRUE":
            return lit(True)
        if up == "FALSE":
            return lit(False)
        if up == "DATE":
            s = self._next().text
            y, m, d = s.split("-")
            return lit(datetime.date(int(y), int(m), int(d)))
        if up == "TIMESTAMP":
            s = self._next().text
            return lit(datetime.datetime.fromisoformat(s))
        if up == "INTERVAL":
            s = self._next().text
            qty, unit = s.split(" ", 1) if " " in s else (s, self._next().text)
            return _interval(int(qty), unit)
        if up == "EXISTS" and self._peek().text == "(":
            from ..logical import subquery as subq
            self._next()
            info = self._parse_subquery(scope)
            self._expect(")")
            return subq.exists_expr(info)
        if up == "CASE":
            return self._case(scope)
        if up == "CAST":
            self._expect("(")
            e = self._expr(scope)
            self._expect("AS")
            tname = self._next().text
            dt = _sql_type(tname, self)
            self._expect(")")
            return e.cast(dt)
        if up == "EXTRACT":
            self._expect("(")
            part = self._next().text.lower()
            self._expect("FROM")
            e = self._expr(scope)
            self._expect(")")
            return getattr(e.dt, part)()
        # function call?
        if self._peek().text == "(":
            return self._function(t.text, scope)
        # qualified identifier
        if self._peek().text == ".":
            self._next()
            colname = self._next().text
            if scope is None:
                return col(colname)
            return self._resolve_col(scope, colname, t.text)
        if scope is None:
            return col(t.text)
        return self._resolve_col(scope, t.text)

    def _case(self, scope) -> Expression:
        base = None
        if not self._peek_kw("WHEN"):
            base = self._expr(scope)
        branches = []
        while self._kw("WHEN"):
            cond = self._expr(scope)
            self._expect("THEN")
            val = self._expr(scope)
            branches.append((cond, val))
        default = lit(None)
        if self._kw("ELSE"):
            default = self._expr(scope)
        self._expect("END")
        out = default
        for cond, val in reversed(branches):
            c = (base == cond) if base is not None else cond
            out = c.if_else(val, out)
        return out

    def _function(self, name: str, scope) -> Expression:
        self._expect("(")
        fn = name.lower()
        distinct = False
        if fn == "count" and self._peek().text == "*":
            self._next()
            self._expect(")")
            return lit(1).count("all").alias("count")
        if self._kw("DISTINCT"):
            distinct = True
        args: List[Expression] = []
        if self._peek().text != ")":
            while True:
                args.append(self._expr(scope))
                if not self._kw(","):
                    break
        self._expect(")")
        if self._peek_kw("OVER"):
            return self._window_call(fn, args, scope)
        try:
            return _apply_function(fn, args, distinct)
        except ValueError as e:
            # not a built-in: fall back to session-attached UDFs (built-ins
            # keep precedence so attaching e.g. "sum" can't shadow SUM)
            if (str(e).startswith("unknown SQL function")
                    and self.session is not None
                    and fn in self.session._functions):
                if distinct:
                    raise ValueError(
                        f"DISTINCT is not supported for attached UDF {fn!r}")
                return self.session._functions[fn](*args)
            raise

    # -- window functions --------------------------------------------------
    _WINDOW_FNS = {"row_number", "rank", "dense_rank", "lag", "lead",
                   "sum", "avg", "mean", "min", "max", "count", "ntile"}

    def _window_call(self, fn: str, args: List[Expression],
                     scope) -> Expression:
        """``fn(args) OVER (PARTITION BY … ORDER BY … [frame])`` →
        Expression.over(Window) on the DataFrame window path
        (reference: ``src/daft-sql/src/modules/window.rs``)."""
        from ..window import Window
        self._kw("OVER")
        self._expect("(")
        w = Window()
        if self._kw("PARTITION"):
            self._expect("BY")
            parts = []
            while True:
                parts.append(self._expr(scope))
                if not self._kw(","):
                    break
            w = w.partition_by(*parts)
        if self._kw("ORDER"):
            self._expect("BY")
            obs, descs = [], []
            while True:
                obs.append(self._expr(scope))
                if self._kw("DESC"):
                    descs.append(True)
                else:
                    self._kw("ASC")
                    descs.append(False)
                if not self._kw(","):
                    break
            w = w.order_by(*obs, desc=descs)
        if self._peek_kw("ROWS") or self._peek_kw("RANGE"):
            mode = self._next().text.lower()
            w = self._window_frame(w, mode)
        self._expect(")")

        if fn not in self._WINDOW_FNS:
            raise ValueError(f"unsupported window function {fn!r}")
        if fn == "row_number":
            from ..functions import row_number
            return row_number().over(w)
        if fn == "rank":
            from ..functions import rank
            return rank().over(w)
        if fn == "dense_rank":
            from ..functions import dense_rank
            return dense_rank().over(w)
        if fn in ("lag", "lead"):
            if not args:
                raise ValueError(f"{fn} requires an argument")
            offset = 1
            default = None
            if len(args) >= 2:
                if args[1].op != "lit":
                    raise ValueError(f"{fn} offset must be a literal")
                offset = int(args[1].params[0])
            if len(args) >= 3:
                default = args[2]
            base = args[0]
            e = base.lag(offset, default) if fn == "lag" \
                else base.lead(offset, default)
            return e.over(w)
        # windowed aggregates
        agg = _apply_function("avg" if fn == "mean" else fn, args, False)
        return agg.over(w)

    def _window_frame(self, w, mode: str):
        from ..window import Window
        self._expect("BETWEEN")

        def bound():
            if self._kw("UNBOUNDED"):
                if self._kw("PRECEDING"):
                    return Window.unbounded_preceding
                self._expect("FOLLOWING")
                return Window.unbounded_following
            if self._kw("CURRENT"):
                self._expect("ROW")
                return 0
            n = int(self._next().text)
            if self._kw("PRECEDING"):
                return -n
            self._expect("FOLLOWING")
            return n

        lo = bound()
        self._expect("AND")
        hi = bound()
        if mode == "rows":
            return w.rows_between(lo, hi)
        return w.range_between(lo, hi)


class _LenientScope:
    """ORDER BY may reference projection output aliases not yet in scope."""

    def __init__(self, scope: Scope):
        self._scope = scope
        self.tables = scope.tables
        self.order = scope.order

    def resolve(self, name: str, alias: Optional[str] = None) -> str:
        try:
            return self._scope.resolve(name, alias)
        except ValueError:
            return name

    def all_columns(self):
        return self._scope.all_columns()


_CLAUSE_WORDS = {"ON", "USING", "WHERE", "GROUP", "ORDER", "LIMIT", "HAVING",
                 "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "UNION",
                 "INTERSECT", "EXCEPT", "AS", "SEMI", "ANTI", "OFFSET",
                 "OUTER", "AND", "OR", "NOT", "SELECT", "FROM", "WITH", "BY"}


def _interval(qty: int, unit: str) -> Expression:
    unit = unit.lower().rstrip("s")
    kw = {"year": "years", "month": "months", "day": "days", "hour": "hours",
          "minute": "minutes", "second": "seconds"}[unit]
    from ..expressions.expressions import interval
    return interval(**{kw: qty})


def _sql_type(name: str, planner: SQLPlanner) -> DataType:
    n = name.lower()
    m = {"int": DataType.int32, "integer": DataType.int32,
         "bigint": DataType.int64, "smallint": DataType.int16,
         "tinyint": DataType.int8, "float": DataType.float32,
         "real": DataType.float32, "double": DataType.float64,
         "text": DataType.string, "varchar": DataType.string,
         "string": DataType.string, "boolean": DataType.bool,
         "bool": DataType.bool, "date": DataType.date,
         "binary": DataType.binary, "bytea": DataType.binary,
         "timestamp": lambda: DataType.timestamp(TimeUnit.us)}
    if n == "decimal" or n == "numeric":
        if planner._peek().text == "(":
            planner._next()
            p = int(planner._next().text)
            planner._expect(",")
            s = int(planner._next().text)
            planner._expect(")")
            return DataType.decimal128(p, s)
        return DataType.decimal128(38, 10)
    if n in ("varchar", "char") and planner._peek().text == "(":
        planner._next()
        planner._next()
        planner._expect(")")
        return DataType.string()
    if n not in m:
        raise ValueError(f"unknown SQL type {name!r}")
    return m[n]()


def _expand_group_items(items):
    """GROUP BY item list → (all keys in first-appearance order, grouping
    sets or None). A plain-only list returns ``(keys, None)`` — the single
    groupby fast path. Mixed items cross-product per SQL:
    ``GROUP BY x, ROLLUP(a, b)`` → sets {x,a,b}, {x,a}, {x}."""
    if all(kind == "plain" for kind, _ in items):
        return [p[0] for _, p in items], None
    import itertools as it
    base: List[List[Expression]] = [[]]
    for kind, payload in items:
        if kind == "plain":
            opts = [[payload[0]]]
        elif kind == "rollup":
            opts = [list(payload[:i]) for i in range(len(payload), -1, -1)]
        elif kind == "cube":
            opts = [list(c) for r in range(len(payload), -1, -1)
                    for c in it.combinations(payload, r)]
        else:  # explicit GROUPING SETS
            opts = [list(s) for s in payload]
        base = [b + o for b in base for o in opts]

    def dedupe(ks):
        out = []
        for k in ks:
            if not any(k._unalias().structurally_eq(x._unalias())
                       for x in out):
                out.append(k)
        return out

    all_keys = dedupe([k for kind, payload in items
                       for k in (payload if kind != "sets"
                                 else [x for s in payload for x in s])])
    uniq, seen = [], set()
    for S in base:
        S = dedupe(S)
        sk = tuple(sorted(a._unalias()._key() for a in S))
        if sk not in seen:
            seen.add(sk)
            uniq.append(S)
    return all_keys, uniq


def _apply_function(fn: str, args: List[Expression],
                    distinct: bool) -> Expression:
    a = args[0] if args else None
    if fn == "grouping":
        # GROUPING(key) marker: resolved to a per-branch literal (0 = key
        # grouped, 1 = super-aggregate NULL) by the ROLLUP/CUBE/GROUPING
        # SETS lowering; reaching execution unresolved is an error.
        return Expression("sql.grouping", (a,))
    if fn in ("sum",):
        return a.sum()
    if fn in ("avg", "mean"):
        return a.mean()
    if fn == "min":
        return a.min()
    if fn == "max":
        return a.max()
    if fn == "count":
        return a.count_distinct() if distinct else a.count()
    if fn in ("stddev", "stddev_pop"):
        return a.stddev()
    if fn in ("var", "variance"):
        return a.var()
    if fn == "any_value":
        return a.any_value()
    if fn == "approx_count_distinct":
        return a.approx_count_distinct()
    if fn == "abs":
        return abs(a)
    if fn == "round":
        return a.round(int(args[1].params[0]) if len(args) > 1 else 0)
    if fn in ("ceil", "ceiling"):
        return a.ceil()
    if fn == "floor":
        return a.floor()
    if fn == "sqrt":
        return a.sqrt()
    if fn in ("ln",):
        return a.ln()
    if fn == "log":
        return a.log10() if len(args) == 1 else args[1].log(args[0].params[0])
    if fn == "exp":
        return a.exp()
    if fn == "power" or fn == "pow":
        return a ** args[1]
    if fn == "coalesce":
        return coalesce(*args)
    if fn == "nullif":
        return (a == args[1]).if_else(lit(None), a)
    if fn == "upper":
        return a.str.upper()
    if fn == "lower":
        return a.str.lower()
    if fn in ("length", "char_length"):
        return a.str.length()
    if fn == "trim":
        return a.str.strip()
    if fn == "ltrim":
        return a.str.lstrip()
    if fn == "rtrim":
        return a.str.rstrip()
    if fn == "reverse":
        return a.str.reverse()
    if fn in ("substr", "substring"):
        start = args[1] - 1  # SQL is 1-based
        length = args[2] if len(args) > 2 else None
        return a.str.substr(start, length)
    if fn == "replace":
        return a.str.replace(args[1], args[2])
    if fn == "starts_with":
        return a.str.startswith(args[1])
    if fn == "ends_with":
        return a.str.endswith(args[1])
    if fn == "contains":
        return a.str.contains(args[1])
    if fn == "concat":
        out = args[0]
        for x in args[1:]:
            out = out.str.concat(x)
        return out
    if fn == "split":
        return a.str.split(args[1])
    if fn in ("regexp_match",):
        return a.str.match(args[1].params[0])
    if fn in ("regexp_extract",):
        return a.str.extract(args[1], 0)
    if fn in ("regexp_extract_all",):
        return a.str.extract_all(args[1], 0)
    if fn in ("regexp_replace",):
        return a.str.replace(args[1], args[2], regex=True)
    if fn in ("lpad", "rpad"):
        length = args[1]
        pad = args[2] if len(args) > 2 else Expression._lit(" ")
        ns = a.str
        return (ns.lpad if fn == "lpad" else ns.rpad)(length, pad)
    if fn == "repeat":
        return a.str.repeat(args[1])
    if fn == "normalize":
        return a.str.normalize()
    if fn in ("starts_with", "startswith"):
        return a.str.startswith(args[1])
    if fn in ("ends_with", "endswith"):
        return a.str.endswith(args[1])
    if fn in ("ltrim",):
        return a.str.lstrip()
    if fn in ("rtrim",):
        return a.str.rstrip()
    if fn in ("trim",):
        return a.str.strip()
    if fn == "reverse":
        return a.str.reverse()
    if fn == "capitalize":
        return a.str.capitalize()
    if fn in ("left",):
        return a.str.left(args[1])
    if fn in ("right",):
        return a.str.right(args[1])
    if fn in ("find", "instr"):
        return a.str.find(args[1])
    if fn == "count_matches":
        return a.str.count_matches(args[1].params[0])
    if fn == "tokenize_encode":
        return a.str.tokenize_encode(args[1].params[0])
    if fn == "tokenize_decode":
        return a.str.tokenize_decode(args[1].params[0])
    if fn in ("year", "month", "day", "hour", "minute", "second", "quarter"):
        return getattr(a.dt, fn)()
    if fn == "day_of_week" or fn == "dayofweek":
        return a.dt.day_of_week()
    if fn == "date_trunc":
        return args[1].dt.truncate(args[0].params[0])
    if fn == "to_date":
        return a.str.to_date(args[1].params[0] if len(args) > 1 else "%Y-%m-%d")
    if fn == "if" or fn == "iif":
        return a.if_else(args[1], args[2])
    if fn == "greatest":
        from ..functions import columns_max
        return columns_max(*args)
    if fn == "least":
        from ..functions import columns_min
        return columns_min(*args)
    if fn == "hash":
        return a.hash()
    if fn == "row_number":
        from ..functions import row_number
        return row_number()
    if fn == "rank":
        from ..functions import rank
        return rank()
    if fn == "dense_rank":
        from ..functions import dense_rank
        return dense_rank()
    if fn == "list_value_counts":
        return a.list.value_counts()
    raise ValueError(f"unknown SQL function {fn!r}")


def _has_agg(e: Expression) -> bool:
    # an aggregate INSIDE an OVER(...) window is not a groupby aggregate —
    # it rides the Window plan node instead
    if e.op == "window":
        return False
    if e.op.startswith("agg."):
        return True
    return any(_has_agg(c) for c in e.args)


def _contains_window(e: Expression) -> bool:
    if e.op == "window":
        return True
    return any(_contains_window(c) for c in e.args)


def _split_join_condition(cond: Expression, left_scope: Scope,
                          right_scope: Scope):
    """ON clause → (left_on, right_on, residual_filter)."""
    from ..logical.optimizer import split_conjuncts, combine_conjuncts
    left_cols = set()
    for a in left_scope.order:
        left_cols.update(left_scope.tables[a].values())
    right_cols = set()
    for a in right_scope.order:
        right_cols.update(right_scope.tables[a].values())
    lo, ro, rest = [], [], []
    for c in split_conjuncts(cond):
        if c.op == "eq":
            l, r = c.args
            lc, rc = set(l.column_names()), set(r.column_names())
            if lc <= left_cols and rc <= right_cols:
                lo.append(l)
                ro.append(r)
                continue
            if lc <= right_cols and rc <= left_cols:
                lo.append(r)
                ro.append(l)
                continue
        rest.append(c)
    if not lo:
        raise ValueError("join ON clause needs at least one equality "
                         "between left and right columns")
    residual = combine_conjuncts(rest) if rest else None
    return lo, ro, residual


def _rebind_order(e: Expression, proj: List[Expression]) -> Expression:
    """ORDER BY may reference either output aliases or projected expressions."""
    for p in proj:
        if e.structurally_eq(p._unalias()) or e.structurally_eq(p):
            return col(p.name())
        if e.op == "col" and e.params[0] == p.name():
            return e
    if _contains_grouping(e):
        # ``ORDER BY CASE WHEN GROUPING(a)+GROUPING(b) = 0 THEN a END``
        # (TPC-DS Q70/Q86): GROUPING() exists only inside the per-branch
        # rollup lowering — rebind any subtree that matches a projected
        # item's body to that output column (``lochierarchy``-style)
        def sub(x: Expression) -> Expression:
            for p in proj:
                if x.structurally_eq(p._unalias()):
                    return col(p.name())
            if not x.args:
                return x
            return x.with_children([sub(a) for a in x.args])
        e = sub(e)
        if _contains_grouping(e):
            raise NotImplementedError(
                "GROUPING() in ORDER BY must match a projected "
                "expression (e.g. project it AS lochierarchy)")
    return e


def _contains_grouping(e: Expression) -> bool:
    if e.op == "sql.grouping":
        return True
    return any(_contains_grouping(c) for c in e.args)
