from .sql import sql, sql_expr, SQLCatalog

__all__ = ["sql", "sql_expr", "SQLCatalog"]
