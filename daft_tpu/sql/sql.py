"""daft_tpu.sql — SQL → LogicalPlan frontend entry points.

Reference: ``daft/sql/sql.py`` (binding against in-scope DataFrames via
SQLCatalog) over ``src/daft-sql``'s planner. The parser/planner itself lives
in ``planner.py`` (hand-written recursive descent — no third-party SQL
dependency exists in this environment).
"""

from __future__ import annotations

import inspect
from typing import Dict, Optional


class SQLCatalog:
    def __init__(self, tables: Dict[str, "object"]):
        self.tables = dict(tables)

    def register_table(self, name: str, df):
        self.tables[name] = df


def sql(query: str, catalog: Optional[SQLCatalog] = None, **kwargs):
    """Run SQL against DataFrames bound by name (caller locals or catalog)."""
    from .planner import SQLPlanner
    from ..dataframe import DataFrame
    tables = {}
    if catalog is None:
        frame = inspect.currentframe().f_back
        for scope in (frame.f_globals, frame.f_locals):
            for k, v in scope.items():
                if isinstance(v, DataFrame):
                    tables[k] = v
    else:
        tables.update(catalog.tables)
    tables.update({k: v for k, v in kwargs.items()
                   if isinstance(v, DataFrame)})
    from .. import session as _sess
    return SQLPlanner(tables, session=_sess._session()).plan_statement(query)


def sql_expr(expr: str):
    from .planner import SQLPlanner
    return SQLPlanner({}).plan_expression(expr)
