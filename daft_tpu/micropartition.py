"""MicroPartition: lazily-materialized unit of data movement.

Reference: ``src/daft-micropartition/src/micropartition.rs:36-90`` —
``TableState::{Unloaded(ScanTask), Loaded(Vec<RecordBatch>)}``; an unloaded
partition carries its scan task + stats and materializes on first touch. All
logical ops are mirrored at this level so unloaded partitions can flow through
the executor with metadata-only handling.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from .expressions import Expression
from .recordbatch import RecordBatch
from .schema import Schema
from .series import Series


class MicroPartition:
    """Either loaded batches or a thunk that produces them (a ScanTask)."""

    def __init__(self, schema: Schema,
                 batches: Optional[List[RecordBatch]] = None,
                 scan_task: Optional[Any] = None,
                 metadata_num_rows: Optional[int] = None,
                 metadata_size_bytes: Optional[int] = None):
        assert (batches is None) != (scan_task is None)
        self._schema = schema
        self._batches = batches
        self._scan_task = scan_task
        self._meta_rows = metadata_num_rows
        self._meta_bytes = metadata_size_bytes
        self._lock = threading.Lock()

    def __getstate__(self):
        # partitions cross process boundaries (actor IPC, remote workers);
        # the load lock is per-process state
        d = self.__dict__.copy()
        d.pop("_lock", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._lock = threading.Lock()

    # ---- constructors ----------------------------------------------------
    @classmethod
    def from_recordbatch(cls, rb: RecordBatch) -> "MicroPartition":
        return cls(rb.schema, batches=[rb])

    @classmethod
    def from_recordbatches(cls, rbs: List[RecordBatch],
                           schema: Optional[Schema] = None) -> "MicroPartition":
        assert rbs or schema is not None
        return cls(schema or rbs[0].schema, batches=list(rbs))

    @classmethod
    def from_scan_task(cls, scan_task) -> "MicroPartition":
        return cls(scan_task.materialized_schema(), scan_task=scan_task,
                   metadata_num_rows=scan_task.num_rows(),
                   metadata_size_bytes=scan_task.size_bytes())

    @classmethod
    def empty(cls, schema: Optional[Schema] = None) -> "MicroPartition":
        schema = schema or Schema.empty()
        return cls(schema, batches=[RecordBatch.empty(schema)])

    @classmethod
    def from_pydict(cls, data: Dict[str, Any]) -> "MicroPartition":
        return cls.from_recordbatch(RecordBatch.from_pydict(data))

    @classmethod
    def from_arrow_table(cls, t: pa.Table) -> "MicroPartition":
        return cls.from_recordbatch(RecordBatch.from_arrow_table(t))

    # ---- state -----------------------------------------------------------
    @property
    def schema(self) -> Schema:
        return self._schema

    def is_loaded(self) -> bool:
        return self._batches is not None

    def _load(self) -> List[RecordBatch]:
        with self._lock:
            if self._batches is None:
                batches = self._scan_task.execute()
                self._batches = [b.cast_to_schema(self._schema) for b in batches]
                self._scan_task = None
            return self._batches

    def combined(self) -> RecordBatch:
        bs = self._load()
        if len(bs) == 1:
            return bs[0]
        if not bs:
            return RecordBatch.empty(self._schema)
        merged = RecordBatch.concat(bs)
        with self._lock:
            self._batches = [merged]
        return merged

    def batches(self) -> List[RecordBatch]:
        return list(self._load())

    def __len__(self) -> int:
        if self._batches is None and self._meta_rows is not None:
            return self._meta_rows
        return sum(len(b) for b in self._load())

    def size_bytes(self) -> int:
        if self._batches is None and self._meta_bytes is not None:
            return self._meta_bytes
        return sum(b.size_bytes() for b in self._load())

    def metadata_num_rows(self) -> Optional[int]:
        """Row count without forcing a load (None if unknown)."""
        if self._batches is not None:
            return sum(len(b) for b in self._batches)
        return self._meta_rows

    # ---- mirrored ops (load-on-touch) -----------------------------------
    def eval_expression_list(self, exprs: Sequence[Expression]) -> "MicroPartition":
        out = self.combined().eval_expression_list(list(exprs))
        return MicroPartition.from_recordbatch(out)

    def filter(self, predicate: Expression) -> "MicroPartition":
        return MicroPartition.from_recordbatch(self.combined().filter(predicate))

    def head(self, n: int) -> "MicroPartition":
        return MicroPartition.from_recordbatch(self.combined().head(n))

    def sample(self, **kwargs) -> "MicroPartition":
        return MicroPartition.from_recordbatch(self.combined().sample(**kwargs))

    def sort(self, keys, descending=None, nulls_first=None) -> "MicroPartition":
        return MicroPartition.from_recordbatch(
            self.combined().sort(keys, descending, nulls_first))

    def agg(self, to_agg, group_by=()) -> "MicroPartition":
        return MicroPartition.from_recordbatch(
            self.combined().agg(to_agg, group_by))

    def distinct(self, on=None) -> "MicroPartition":
        return MicroPartition.from_recordbatch(self.combined().distinct(on))

    def explode(self, exprs) -> "MicroPartition":
        return MicroPartition.from_recordbatch(self.combined().explode(exprs))

    def unpivot(self, ids, values, variable_name, value_name) -> "MicroPartition":
        return MicroPartition.from_recordbatch(
            self.combined().unpivot(ids, values, variable_name, value_name))

    def pivot(self, group_by, pivot_col, value_col, names) -> "MicroPartition":
        return MicroPartition.from_recordbatch(
            self.combined().pivot(group_by, pivot_col, value_col, names))

    def hash_join(self, right: "MicroPartition", left_on, right_on,
                  how="inner") -> "MicroPartition":
        return MicroPartition.from_recordbatch(
            self.combined().hash_join(right.combined(), left_on, right_on, how))

    def cross_join(self, right: "MicroPartition") -> "MicroPartition":
        return MicroPartition.from_recordbatch(
            self.combined().cross_join(right.combined()))

    def concat(self, others: List["MicroPartition"]) -> "MicroPartition":
        batches = self.batches()
        for o in others:
            batches.extend(o.batches())
        return MicroPartition.from_recordbatches(batches, self._schema)

    def partition_by_hash(self, exprs, num_partitions) -> List["MicroPartition"]:
        return [MicroPartition.from_recordbatch(b)
                for b in self.combined().partition_by_hash(exprs, num_partitions)]

    def partition_by_random(self, num_partitions, seed) -> List["MicroPartition"]:
        return [MicroPartition.from_recordbatch(b)
                for b in self.combined().partition_by_random(num_partitions, seed)]

    def partition_by_range(self, keys, boundaries, descending) -> List["MicroPartition"]:
        return [MicroPartition.from_recordbatch(b)
                for b in self.combined().partition_by_range(keys, boundaries,
                                                            descending)]

    def add_monotonically_increasing_id(self, partition_num, column_name):
        return MicroPartition.from_recordbatch(
            self.combined().add_monotonically_increasing_id(partition_num,
                                                            column_name))

    def cast_to_schema(self, schema: Schema) -> "MicroPartition":
        if self._batches is None:
            return MicroPartition(schema, scan_task=self._scan_task,
                                  metadata_num_rows=self._meta_rows,
                                  metadata_size_bytes=self._meta_bytes)
        return MicroPartition.from_recordbatches(
            [b.cast_to_schema(schema) for b in self._batches], schema)

    def to_arrow_table(self) -> pa.Table:
        return self.combined().to_arrow_table()

    def to_pydict(self) -> Dict[str, list]:
        return self.combined().to_pydict()

    def __repr__(self):
        state = "Loaded" if self.is_loaded() else "Unloaded"
        return f"MicroPartition[{state}]({self._schema}, rows={self.metadata_num_rows()})"
