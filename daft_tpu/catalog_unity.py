"""Unity Catalog adapter over the open REST API.

Reference capability: ``/root/reference/daft/unity_catalog/`` +
``daft/catalog/__init__.py``'s Unity adapter (SDK-based). This one is
SDK-free: the open Unity Catalog REST surface (``/api/2.1/unity-catalog``)
provides schema/table listing and table metadata (storage location + data
source format); reads route through the native Delta/Iceberg/parquet
readers against that location.

Attach to a session like any catalog::

    cat = UnityCatalog("http://localhost:8080", token=..., catalog="unity")
    sess.attach(cat, alias="uc")
    sess.sql("SELECT * FROM uc.sales.orders")
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, List, Optional

from .catalog import Catalog, Identifier, NotFoundError, Table


class UnityTable(Table):
    """One Unity table: reads dispatch on data_source_format against
    storage_location."""

    def __init__(self, name: str, storage_location: str, fmt: str,
                 io_config=None):
        self._name = name
        self.storage_location = storage_location
        self.format = (fmt or "DELTA").upper()
        self._io_config = io_config

    @property
    def name(self) -> str:
        return self._name

    def schema(self):
        return self.read().schema()

    def read(self, **options: Any):
        import daft_tpu as dt
        options.setdefault("io_config", self._io_config)
        if self.format == "DELTA":
            return dt.read_deltalake(self.storage_location, **options)
        if self.format == "ICEBERG":
            return dt.read_iceberg(self.storage_location, **options)
        if self.format == "PARQUET":
            return dt.read_parquet(
                self.storage_location.rstrip("/") + "/**/*.parquet",
                **options)
        raise NotImplementedError(
            f"unity table format {self.format!r}")


class UnityCatalog(Catalog):
    """Read-side Unity Catalog client (list/get; writes go through the
    table's underlying format)."""

    def __init__(self, endpoint: str, token: Optional[str] = None,
                 catalog: str = "unity", name: Optional[str] = None,
                 io_config=None):
        self.endpoint = endpoint.rstrip("/")
        self.token = token
        self.catalog = catalog
        self._name = name or catalog
        self._io_config = io_config

    @property
    def name(self) -> str:
        return self._name

    # ------------------------------------------------------------- REST
    def _request(self, path: str, params: Optional[dict] = None) -> dict:
        url = f"{self.endpoint}/api/2.1/unity-catalog/{path}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(url)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                raise NotFoundError(f"unity: {path} not found") from exc
            raise

    # -------------------------------------------------------------- SPI
    def _get_table(self, ident: Identifier) -> Table:
        if len(ident) == 2:
            full = f"{self.catalog}.{ident[0]}.{ident[1]}"
        elif len(ident) == 3:
            full = str(ident)
        else:
            raise NotFoundError(
                f"unity table names are schema.table (got {ident})")
        doc = self._request(f"tables/{urllib.parse.quote(full, safe='.')}")
        loc = doc.get("storage_location")
        if not loc:
            raise NotFoundError(f"unity table {full} has no storage "
                                f"location")
        return UnityTable(ident[-1], loc,
                          doc.get("data_source_format", "DELTA"),
                          self._io_config)

    def _paged(self, path: str, params: dict, key: str):
        """Drain a paginated Unity list endpoint (next_page_token)."""
        token = None
        while True:
            p = dict(params)
            if token:
                p["page_token"] = token
            doc = self._request(path, p)
            yield from doc.get(key, [])
            token = doc.get("next_page_token")
            if not token:
                return

    def _list_namespaces(self, pattern: Optional[str] = None
                         ) -> List[Identifier]:
        out = [Identifier(s["name"]) for s in
               self._paged("schemas", {"catalog_name": self.catalog},
                           "schemas")]
        return [i for i in out
                if pattern is None or str(i).startswith(pattern)]

    def _list_tables(self, pattern: Optional[str] = None
                     ) -> List[Identifier]:
        out: List[Identifier] = []
        for ns in self._list_namespaces():
            out.extend(Identifier(ns[0], t["name"]) for t in
                       self._paged("tables",
                                   {"catalog_name": self.catalog,
                                    "schema_name": ns[0]}, "tables"))
        return [i for i in out
                if pattern is None or str(i).startswith(pattern)]
