"""daft_tpu.serving — the concurrent serving plane.

A driver-level :class:`QueryScheduler` admits N concurrent queries
against shared engine resources: per-session weighted fair queuing,
cost-model admission control against a byte budget, compiled-plan and
result caches keyed by logical-plan fingerprints, and cooperative
cancellation threaded into the executor pipelines. The Spark Connect
server routes every ``ExecutePlan`` through the process-shared scheduler;
``bench.py --serve`` drives it with sustained mixed traffic.

Horizontal scale-out lives in ``daft_tpu.fleet``: N replica processes
each host one shared scheduler like this one; the scheduler transparently
consults the process-installed fleet state store (gossiped calibration +
admission history) and cache tier when present, and grows ``drain`` /
``release_session`` lifecycle hooks for the router.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..execution.cancellation import CancelToken, QueryCancelled
from .caches import PlanCache, ResultCache
from .scheduler import AdmissionRejected, QueryHandle, QueryScheduler

__all__ = [
    "AdmissionRejected", "CancelToken", "PlanCache", "QueryCancelled",
    "QueryHandle", "QueryScheduler", "ResultCache", "shared_scheduler",
    "shared_scheduler_if_running", "shutdown_shared",
]

_shared_lock = threading.Lock()
_shared: Optional[QueryScheduler] = None


def shared_scheduler() -> QueryScheduler:
    """The process-wide scheduler (lazily built from the serve knobs);
    the Spark Connect front door submits through this one so all client
    sessions share one admission budget and one set of caches."""
    global _shared
    if _shared is not None:  # hot path: no lock once built
        return _shared
    with _shared_lock:
        if _shared is None:
            _shared = QueryScheduler()
        return _shared


def shared_scheduler_if_running() -> Optional[QueryScheduler]:
    """The shared scheduler if one exists (the dashboard's live queue
    view must not boot a scheduler as a side effect of being looked at)."""
    return _shared


def shutdown_shared() -> None:
    global _shared
    with _shared_lock:
        sched = _shared
        _shared = None
    if sched is not None:
        sched.shutdown()
