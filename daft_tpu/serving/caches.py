"""Serving-plane caches: compiled plans and materialized results.

Both are byte-budgeted LRUs keyed by the logical-plan fingerprint
(``logical/fingerprint.py`` — literal-stripped structure + bound-parameter
vector + source versions; see that module for the invalidation rules).

- :class:`PlanCache` amortizes ``optimize() + translate()`` and keeps the
  translated physical plan's scan tasks (footer reads already done) warm;
  because the device tier's jit caches key on expression fingerprints,
  a plan-cache hit also re-enters every previously-compiled device
  fragment without recompiling — the 11s warm-up (BENCH_r02/r04) is paid
  once per plan shape, not per submission.
- :class:`ResultCache` short-circuits execution entirely for an identical
  literal-inclusive fingerprint over unchanged sources. Entries are
  immutable ``PartitionSet``s and account their real ``size_bytes()``.

Thread-safe; hit/miss/eviction counters feed the serving stats block and
``bench.py --serve``.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, Optional, Tuple


class _LRUCache:
    """Byte-budgeted LRU with counters. ``budget <= 0`` disables it."""

    def __init__(self, budget_bytes: int):
        self.budget = int(budget_bytes)
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[Tuple, Tuple[object, int]]" \
            = collections.OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def enabled(self) -> bool:
        return self.budget > 0

    def get(self, key: Tuple):
        if not self.enabled or key is None:
            return None
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return ent[0]

    def put(self, key: Tuple, value, nbytes: int) -> None:
        if not self.enabled or key is None:
            return
        nbytes = max(int(nbytes), 1)
        if nbytes > self.budget:
            return  # a single over-budget entry would evict everything
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            while self._bytes > self.budget and self._entries:
                _, (_, sz) = self._entries.popitem(last=False)
                self._bytes -= sz
                self.evictions += 1

    def invalidate(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions, "entries":
                    len(self._entries), "bytes": self._bytes,
                    "budget": self.budget}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class PlanCache(_LRUCache):
    """fingerprint.key → (optimized logical plan, translated physical
    plan). Entries are plan trees — small; accounted at a flat estimate
    per node so the budget still bounds growth. Also tracks *structure*
    hits: a submission whose literal-stripped shape was seen before (even
    with different bound parameters) reuses the device tier's jitted
    fragments, which the serving block reports as evidence."""

    _NODE_COST = 2048  # bytes charged per plan node (descriptor-sized)

    def __init__(self, budget_bytes: int):
        super().__init__(budget_bytes)
        self._structures: Dict[str, int] = {}
        self.structure_hits = 0

    @staticmethod
    def _tree_size(node) -> int:
        return 1 + sum(PlanCache._tree_size(c)
                       for c in getattr(node, "children", ()))

    def get_plan(self, fp):
        if fp is None:
            return None
        with self._lock:
            seen = fp.structure in self._structures
            if seen:
                self.structure_hits += 1
        hit = self.get(fp.key)
        return hit

    def put_plan(self, fp, optimized_plan, physical_plan) -> None:
        if fp is None or not self.enabled:
            return
        nbytes = self._NODE_COST * (self._tree_size(optimized_plan)
                                    + self._tree_size(physical_plan))
        self.put(fp.key, (optimized_plan, physical_plan), nbytes)
        with self._lock:
            if len(self._structures) > 65536:  # bound the shape index
                self._structures.clear()
            self._structures[fp.structure] = \
                self._structures.get(fp.structure, 0) + 1

    def stats(self) -> Dict[str, float]:
        out = super().stats()
        out["structure_hits"] = self.structure_hits
        return out


class ResultCache(_LRUCache):
    """fingerprint.key → materialized PartitionSet (immutable)."""

    def get_result(self, fp):
        return self.get(fp.key) if fp is not None else None

    def put_result(self, fp, partition_set) -> None:
        if fp is None or not self.enabled:
            return
        try:
            nbytes = int(partition_set.size_bytes() or 0)
        except Exception:
            return
        self.put(fp.key, partition_set, nbytes)
