"""Multi-tenant query scheduler: the serving plane's control loop.

One process, N concurrent queries, shared engine resources. The pieces:

- **bounded worker pool** — ``DAFT_TPU_SERVE_CONCURRENCY`` workers drain
  a multi-session queue; everything else (executor thread pools, device,
  HBM cache, spill dirs) is the same shared engine the single-query path
  uses.
- **fair queuing** — weighted round-robin across sessions via stride
  scheduling (each dispatch advances the session's virtual ``pass`` by
  ``1/weight``; the non-empty session with the smallest pass goes next),
  FIFO within a session, higher ``priority`` classes always first.
- **admission control** — each query declares an estimated footprint from
  the cost model (``logical/stats.estimate``) and is admitted against a
  shared :class:`~daft_tpu.execution.memory.MemoryManager` byte budget
  (``DAFT_TPU_SERVE_MEMORY``, default: the engine memory limit, else the
  breaker budget) so concurrent queries can't OOM each other: it runs
  when admitted, waits while others drain, and fails with a structured
  :class:`AdmissionRejected` when the queue is full, the queue timeout
  passes, or it could never fit.
- **plan/result caches** — see ``serving/caches.py``; consulted per
  submission, keyed by the logical-plan fingerprint.
- **cooperative cancellation** — every query carries a
  :class:`~daft_tpu.execution.cancellation.CancelToken` threaded into the
  executor pipelines; ``QueryHandle.cancel()`` (or a Spark Connect
  INTERRUPT) unwinds it at the next morsel boundary and releases its
  admission.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

from ..execution.cancellation import CancelToken, QueryCancelled, cancel_scope
from ..execution.memory import MemoryManager, breaker_budget_bytes, \
    memory_limit_bytes
from .caches import PlanCache, ResultCache

_DEFAULT_EST_BYTES = 64 << 20  # footprint guess when the cost model is blind
_MIN_EST_BYTES = 1 << 20

#: per-fingerprint admission-history EWMA weight and retained entries —
#: ROADMAP 4c (minimal): when the cost model is BLIND (no source stats),
#: repeat queries admit their OBSERVED result bytes instead of the flat
#: 64 MiB default, seeded from this process's history and from
#: flight-recorder records of earlier processes
_HIST_ALPHA = 0.3
_HIST_MAX_ENTRIES = 1024


def _history_fingerprint(builder) -> Optional[str]:
    """Stable per-query history key: the literal-inclusive structure
    hash plus the source PATHS — but WITHOUT the size/mtime version
    tokens (a repeat query over refreshed data is still the same
    workload for admission purposes). The paths must participate: the
    canonical structure names sources positionally, so without them a
    same-shape query over a DIFFERENT (much larger) dataset would seed
    its admission estimate from the small one's history and bypass the
    memory gate. None when the plan is unfingerprintable (in-memory
    sources, sinks)."""
    import hashlib

    from ..context import get_context
    from ..logical.fingerprint import fingerprint
    try:
        fp = fingerprint(builder.plan, get_context().execution_config)
    except Exception:
        return None
    return _history_key_from_fp(fp)


def _history_key_from_fp(fp) -> Optional[str]:
    import hashlib
    if fp is None:
        return None
    try:
        # version tuples are (path, *token) — local stat and remote
        # etag tokens have different arities, only the path matters here
        paths = tuple(v[0] for (_t, vers) in fp.sources for v in vers)
    except Exception:
        return None
    # history_structure, NOT structure: the calibration-generation token
    # must not fragment admission history across self-tuning flips or
    # across fleet replicas with different learned profiles
    structure = fp.history_structure or fp.structure
    return hashlib.sha256(
        (structure + "\x00" + repr(fp.params) + "\x00" + repr(paths))
        .encode()).hexdigest()[:16]


class AdmissionRejected(RuntimeError):
    """Structured admission failure. ``kind`` is one of ``queue_full``,
    ``queue_timeout``, ``memory``, ``shutdown``, ``draining`` (the fleet
    router treats the last two as re-routable: the replica is leaving,
    the query belongs on a peer)."""

    def __init__(self, kind: str, message: str,
                 est_bytes: Optional[int] = None,
                 budget: Optional[int] = None,
                 waited_s: float = 0.0):
        super().__init__(message)
        self.kind = kind
        self.est_bytes = est_bytes
        self.budget = budget
        self.waited_s = waited_s


# ------------------------------------------------------------------ knobs

def _knob_int(name: str, cfg_field: str, default: int) -> int:
    from ..analysis import knobs
    v = knobs.env_int(name, default=None)
    if v is not None:
        return v
    try:
        from ..context import get_context
        return int(getattr(get_context().execution_config, cfg_field))
    except Exception:
        return default


def _knob_float(name: str, cfg_field: str, default: float) -> float:
    from ..analysis import knobs
    v = knobs.env_float(name, default=None)
    if v is not None:
        return v
    try:
        from ..context import get_context
        return float(getattr(get_context().execution_config, cfg_field))
    except Exception:
        return default


def serve_concurrency() -> int:
    return max(_knob_int("DAFT_TPU_SERVE_CONCURRENCY",
                         "tpu_serve_concurrency", 4), 1)


def serve_queue_depth() -> int:
    return max(_knob_int("DAFT_TPU_SERVE_QUEUE_DEPTH",
                         "tpu_serve_queue_depth", 64), 1)


def serve_queue_timeout_s() -> float:
    return _knob_float("DAFT_TPU_SERVE_QUEUE_TIMEOUT",
                       "tpu_serve_queue_timeout", 30.0)


def _knob_bytes(name: str, cfg_field: str, default: int) -> int:
    from ..analysis import knobs
    v = knobs.env_bytes(name, default=None)
    if v is not None:
        return v
    try:
        from ..context import get_context
        return int(getattr(get_context().execution_config, cfg_field))
    except Exception:
        return default


def serve_plan_cache_bytes() -> int:
    return _knob_bytes("DAFT_TPU_SERVE_PLAN_CACHE_BYTES",
                       "tpu_serve_plan_cache_bytes", 64 << 20)


def serve_result_cache_bytes() -> int:
    return _knob_bytes("DAFT_TPU_SERVE_RESULT_CACHE_BYTES",
                       "tpu_serve_result_cache_bytes", 64 << 20)


def serve_memory_budget() -> Optional[int]:
    from ..analysis import knobs
    v = knobs.env_bytes("DAFT_TPU_SERVE_MEMORY", default=None)
    if v is not None:
        return v or None  # 0 = unbudgeted admission
    lim = memory_limit_bytes()
    if lim is not None:
        return lim
    return breaker_budget_bytes()


# ------------------------------------------------------------------ handle

class QueryHandle:
    """Client-side view of one submitted query."""

    def __init__(self, scheduler: "QueryScheduler", session: str,
                 priority: int):
        self._scheduler = scheduler
        self.session = session
        self.priority = priority
        self.token = CancelToken()
        self._done = threading.Event()
        self._state_lock = threading.Lock()
        self.state = "queued"      # queued|running|done|failed|cancelled|
        #                            rejected
        self._result = None        # PartitionSet on success
        self._error: Optional[BaseException] = None
        self.stats = None          # RuntimeStatsContext (when executed)
        self.submitted_at = time.monotonic()
        self.submitted_at_us = int(time.time() * 1e6)
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # per-fingerprint admission-history key, set only when the cost
        # model was blind at submit (the history's trigger condition)
        self._fp_hist_key: Optional[str] = None
        # tracing: the query's trace starts at SUBMIT so queue wait is
        # on the timeline; None when tracing is off / sampled out
        from .. import tracing
        self.trace_ctx = tracing.maybe_start_trace("serve")

    # -- completion (scheduler-side) -----------------------------------
    def _finish(self, state: str, result=None,
                error: Optional[BaseException] = None, stats=None) -> None:
        with self._state_lock:
            if self._done.is_set():
                return
            self.state = state
            self._result = result
            self._error = error
            if stats is not None:
                self.stats = stats
            self.finished_at = time.monotonic()
            self._done.set()
        if state in ("rejected", "cancelled"):
            # rejected/cancelled queries never executed — close their
            # trace here so the recorder can't leak ("failed" queries DO
            # export: the run worker finalizes them with error status,
            # they're exactly the traces an operator needs)
            self._end_trace(state)

    def _mark_running(self) -> None:
        with self._state_lock:
            if not self._done.is_set():
                self.state = "running"
                self.started_at = time.monotonic()

    # -- client api ----------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def queue_wait_s(self) -> float:
        start = self.started_at if self.started_at is not None \
            else self.finished_at
        if start is None:
            return time.monotonic() - self.submitted_at
        return max(start - self.submitted_at, 0.0)

    def cancel(self, reason: Optional[str] = None) -> None:
        """Cooperative cancel: a queued query leaves the queue now; a
        running one unwinds at its next morsel boundary."""
        from .. import tracing
        tracing.event("serve:cancel", key="serve:cancel",
                      attrs={"reason": reason or "cancelled by client"},
                      lane="serving", ctx=self.trace_ctx)
        self.token.set(reason or "cancelled by client")
        self._scheduler._cancel_queued(self)

    def _end_trace(self, status: str) -> None:
        """Close and drop a trace that will never reach the per-query
        export path (rejections, cancellations)."""
        if self.trace_ctx is None:
            return
        from .. import tracing
        rec = self.trace_ctx.recorder
        if not rec.exported:
            rec.exported = True
            rec.finish(status)
            tracing.unregister_recorder(rec.trace_id)

    def result(self, timeout: Optional[float] = None):
        """The query's PartitionSet; raises the query's failure,
        AdmissionRejected, or QueryCancelled."""
        if not self._done.wait(timeout):
            raise TimeoutError("query still pending")
        if self.state == "done":
            return self._result
        if self._error is not None:
            raise self._error
        raise QueryCancelled(self.token.reason or "query cancelled")


#: seconds an EMPTY session queue survives before the sweep drops it.
#: Sessions are keyed by client-supplied names (Spark Connect mints a
#: fresh UUID per client session), so without a bound the scheduler's
#: session dict grows for the life of the process; pass/weight memory
#: older than this horizon is fairness-irrelevant (a re-entering session
#: starts at the current minimum pass either way).
_SESSION_IDLE_TTL_S = 60.0


class _SessionQ:
    __slots__ = ("weight", "pass_", "queues", "idle_since")

    def __init__(self, weight: float):
        self.weight = max(float(weight), 1e-6)
        self.pass_ = 0.0
        self.idle_since: Optional[float] = None
        # priority → FIFO of QueryHandle (higher priority served first)
        self.queues: Dict[int, collections.deque] = {}

    def depth(self) -> int:
        return sum(len(d) for d in self.queues.values())


# ---------------------------------------------------------------- scheduler

class QueryScheduler:
    """Admits N concurrent queries against shared engine resources."""

    def __init__(self, concurrency: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 queue_timeout_s: Optional[float] = None,
                 memory_budget: Optional[int] = None,
                 plan_cache_bytes: Optional[int] = None,
                 result_cache_bytes: Optional[int] = None,
                 fleet_state=None, cache_tier=None,
                 name: Optional[str] = None):
        # fleet wiring (both optional): ``fleet_state`` is this replica's
        # fleet/state_sync.StateStore (falls back to the process-installed
        # one), ``cache_tier`` the cross-replica cache layer
        # (fleet/cache_tier); a bare scheduler never touches either
        self.fleet_state = fleet_state
        self.cache_tier = cache_tier
        self.name = name or "driver"
        self.concurrency = concurrency or serve_concurrency()
        self.queue_depth = queue_depth or serve_queue_depth()
        self.queue_timeout_s = queue_timeout_s \
            if queue_timeout_s is not None else serve_queue_timeout_s()
        budget = memory_budget if memory_budget is not None \
            else serve_memory_budget()
        self.admission = MemoryManager(budget)
        if not budget:
            # an explicit 0/None means admission is DISABLED — don't let
            # MemoryManager's own default fall back to the engine limit
            self.admission.budget = None
        self.plan_cache = PlanCache(
            plan_cache_bytes if plan_cache_bytes is not None
            else serve_plan_cache_bytes())
        self.result_cache = ResultCache(
            result_cache_bytes if result_cache_bytes is not None
            else serve_result_cache_bytes())
        self._cond = threading.Condition()
        self._sessions: "collections.OrderedDict[str, _SessionQ]" = \
            collections.OrderedDict()
        self._deadlines: Dict[QueryHandle, Optional[float]] = {}
        self._est: Dict[QueryHandle, int] = {}
        self._builders: Dict[QueryHandle, object] = {}
        self._n_queued = 0
        self._n_running = 0
        self._running: set = set()   # running handles (drain/kill target)
        self._shutdown = False
        self._draining = False
        self._counts_lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        # per-fingerprint admission history (ROADMAP 4c, minimal):
        # key → (ewma result bytes, ewma wall us, samples); consulted
        # only when the cost-model estimate is absent, seeded lazily
        # from the flight recorder so it survives restarts
        self._hist_lock = threading.Lock()
        self._fp_hist: Dict[str, tuple] = {}
        self._flight_seeded = False
        # submit-thread side channel: _estimate_bytes keeps its
        # (self, builder) signature — tests monkeypatch it — so the
        # history key travels per-thread instead of per-call
        self._tl_est = threading.local()
        self._threads: List[threading.Thread] = []
        for i in range(self.concurrency):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"daft-tpu-serve-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        # daft-lint: allow(unattributed-worker) -- the sweep thread only
        # expires queued handles and idle sessions under the scheduler
        # condition; it never executes query work or touches plane
        # counters, so there is no attribution to thread through
        t = threading.Thread(target=self._sweep_loop,
                             name="daft-tpu-serve-sweep", daemon=True)
        t.start()
        self._threads.append(t)
        # AOT warm-up (DAFT_TPU_AOT_WARMUP=1): compile the device
        # program library over the size-class grid BEFORE traffic
        # arrives, so first queries re-enter warm programs; with
        # DAFT_TPU_COMPILE_CACHE_DIR the executables persist across
        # restarts and amortize across replicas.  Never raises; the
        # stats land in the counters for the serve bench to report.
        try:
            from ..device import warmup as _warmup
            w = _warmup.maybe_warmup_session()
            if w:
                self._count("aot_warmup_programs",
                            sum(d.get("programs", 0)
                                for d in w.values()
                                if isinstance(d, dict)))
                self._count("aot_warmup_seconds",
                            float(w.get("seconds", 0.0)))
        except Exception:
            pass

    # ------------------------------------------------------------ counters
    def _count(self, name: str, n: float = 1) -> None:
        with self._counts_lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counters_snapshot(self) -> Dict[str, float]:
        with self._counts_lock:
            out = dict(self._counters)
        out.update({f"plan_cache_{k}": v
                    for k, v in self.plan_cache.stats().items()})
        out.update({f"result_cache_{k}": v
                    for k, v in self.result_cache.stats().items()})
        out["admitted_bytes_outstanding"] = self.admission.outstanding
        return out

    def live_view(self) -> Dict[str, object]:
        """Current queue/admission state for the dashboard."""
        with self._cond:
            sessions = {name: {"queued": s.depth(),
                               "weight": s.weight,
                               "pass": round(s.pass_, 3)}
                        for name, s in self._sessions.items() if s.depth()}
            queued, running = self._n_queued, self._n_running
        return {"queued": queued, "running": running,
                "concurrency": self.concurrency,
                "sessions": sessions,
                "draining": self._draining,
                "admitted_bytes": self.admission.outstanding,
                "admission_budget": self.admission.budget,
                "counters": self.counters_snapshot()}

    def gauges(self) -> Dict[str, float]:
        """Per-replica scale-signal gauges the fleet router aggregates
        (queue depth / admitted bytes are the autoscaling inputs)."""
        with self._cond:
            queued, running = self._n_queued, self._n_running
            sessions = len(self._sessions)
            draining = self._draining
        return {"queued": float(queued), "running": float(running),
                "concurrency": float(self.concurrency),
                "sessions": float(sessions),
                "admitted_bytes": float(self.admission.outstanding),
                "draining": 1.0 if draining else 0.0}

    # --------------------------------------------------------------- fleet
    def _fleet_store(self):
        if self.fleet_state is not None:
            return self.fleet_state
        try:
            from ..fleet import state_sync
            return state_sync.installed()
        except Exception:
            return None

    def _fleet_cache_tier(self):
        if self.cache_tier is not None:
            return self.cache_tier
        try:
            from ..fleet import cache_tier as _ct
            return _ct.installed()
        except Exception:
            return None

    def admission_history_snapshot(self) -> Dict[str, tuple]:
        """Copy of the per-fingerprint admission history — the gossip
        export consumed by ``fleet/state_sync`` (key → (ewma bytes,
        ewma wall us, samples))."""
        with self._hist_lock:
            return dict(self._fp_hist)

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, timeout_s: float = 10.0,
              cancel: bool = True) -> Dict[str, object]:
        """Graceful drain: stop admitting NOW, let queued+running work
        finish within ``timeout_s``, then cooperatively cancel the
        stragglers via their CancelTokens. The scheduler object stays
        alive (caches, counters, gossip exports keep serving) — only
        admission is closed; the fleet router hands the sessions off."""
        deadline = time.monotonic() + max(float(timeout_s), 0.0)
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while self._n_queued or self._n_running:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(min(left, 0.1))
            finished_in_time = not (self._n_queued or self._n_running)
            stragglers: List[QueryHandle] = []
            if cancel and not finished_in_time:
                stragglers = list(self._running)
                stragglers += [h for s in self._sessions.values()
                               for dq in s.queues.values() for h in dq]
        for h in stragglers:  # outside the condition: cancel() re-takes it
            h.cancel("replica draining")
        if stragglers:
            with self._cond:
                grace = time.monotonic() + 5.0
                while self._n_running and time.monotonic() < grace:
                    self._cond.wait(0.1)
        with self._cond:
            remaining = self._n_queued + self._n_running
        self._count("drained")
        return {"finished_in_time": finished_in_time,
                "cancelled": len(stragglers), "remaining": remaining}

    def cancel_all(self, reason: str = "replica killed") -> int:
        """Cooperatively cancel every queued and running query (the
        replica-kill path). Returns the number of handles signalled."""
        with self._cond:
            handles = [h for s in self._sessions.values()
                       for dq in s.queues.values() for h in dq]
            handles += list(self._running)
        for h in handles:
            h.cancel(reason)
        return len(handles)

    def release_session(self, session: str) -> bool:
        """Drop a session's scheduler state NOW (fleet handoff): the
        idle-TTL sweep that would reclaim it after 60s fires immediately
        for the re-homed session, so it can't leak a queue on the old
        replica. Still-queued queries (possible on a hard kill, none
        after a graceful drain) are cancelled. True when it existed."""
        with self._cond:
            s = self._sessions.pop(session, None)
            if s is None:
                return False
            for dq in s.queues.values():
                for h in dq:
                    h._finish("cancelled")
                    self._count("cancelled")
                    self._cleanup(h)
                dq.clear()
            self._n_queued = sum(t.depth()
                                 for t in self._sessions.values())
            self._count("sessions_released")
            self._cond.notify_all()
        return True

    # -------------------------------------------------------------- submit
    def submit(self, query, session: str = "default", priority: int = 0,
               weight: Optional[float] = None,
               timeout_s: Optional[float] = None,
               est_bytes: Optional[int] = None) -> QueryHandle:
        """Enqueue a DataFrame / LogicalPlanBuilder. Always returns a
        handle; a rejection (queue full / timeout / too big) completes
        the handle with :class:`AdmissionRejected`."""
        builder = getattr(query, "_builder", None) or query
        h = QueryHandle(self, session, priority)
        if timeout_s is None:
            timeout_s = self.queue_timeout_s
        deadline = (time.monotonic() + timeout_s) if timeout_s and \
            timeout_s > 0 else None
        # the cost-model estimate may do real IO (remote parquet footer
        # reads materializing scan tasks) — it must never run under the
        # scheduler condition, which every worker/sweep/dashboard pull
        # also needs
        if est_bytes is None:
            est_bytes = self._estimate_bytes(builder)
            # the estimator flags a blind (history-keyed) estimate on
            # the submitting thread; adopt it onto the handle so the
            # completion path can close the loop
            h._fp_hist_key = getattr(self._tl_est, "hist_key", None)
            self._tl_est.hist_key = None
        with self._cond:
            self._count("submitted")
            if self._shutdown:
                h._finish("rejected", error=AdmissionRejected(
                    "shutdown", "scheduler is shut down"))
                self._count("rejected_shutdown")
                return h
            if self._draining:
                # the router treats this as re-routable: the session
                # belongs on a peer replica now
                h._finish("rejected", error=AdmissionRejected(
                    "draining", "replica is draining"))
                self._count("rejected_draining")
                return h
            if self._n_queued >= self.queue_depth:
                h._finish("rejected", error=AdmissionRejected(
                    "queue_full",
                    f"serving queue is full ({self.queue_depth} deep)"))
                self._count("rejected_queue_full")
                return h
            s = self._sessions.get(session)
            if s is None:
                s = self._sessions[session] = _SessionQ(weight or 1.0)
            if weight is not None:
                s.weight = max(float(weight), 1e-6)
            if s.depth() == 0:
                # re-entering session starts at the current minimum pass:
                # idle time must not bank a burst of turns
                active = [t.pass_ for t in self._sessions.values()
                          if t.depth() > 0]
                if active:
                    s.pass_ = max(s.pass_, min(active))
            s.idle_since = None
            s.queues.setdefault(priority, collections.deque()).append(h)
            self._deadlines[h] = deadline
            self._est[h] = est_bytes
            self._builders[h] = builder
            self._n_queued += 1
            # notify_all, not notify: the sweep thread waits on the same
            # condition — waking only it would leave the query undispatched
            # until a worker's 1s timed wait expires
            self._cond.notify_all()
        return h

    def _estimate_bytes(self, builder) -> int:
        # observed history outranks the heuristic model: for a repeat
        # query (same structure + params + source paths) the recorded
        # result bytes of past executions — this process's completions,
        # the flight recorder's, or the fleet's gossiped history on a
        # cold replica — are strictly better information than a
        # selectivity guess, so repeats stop over-/under-admitting
        key = _history_fingerprint(builder)
        self._tl_est.hist_key = key
        if key is not None:
            seeded = self._history_estimate(key)
            if seeded is not None:
                self._count("est_seeded_history")
                return seeded
            seeded = self._fleet_history_estimate(key)
            if seeded is not None:
                self._count("est_seeded_fleet")
                return seeded
        try:
            from ..logical import stats as lstats
            est = lstats.estimate(builder.plan).size_bytes
        except Exception:
            est = None
        if est is None:
            return _DEFAULT_EST_BYTES
        return max(int(est), _MIN_EST_BYTES)

    # ----------------------------------------- admission history (4c)
    def _history_estimate(self, key: str) -> Optional[int]:
        self._seed_history_from_flight()
        with self._hist_lock:
            e = self._fp_hist.get(key)
        if e is None:
            return None
        return max(int(e[0]), _MIN_EST_BYTES)

    def _fleet_history_estimate(self, key: str) -> Optional[int]:
        """Gossiped fleet admission history for ``key`` (sample-weighted
        over replica origins) — a cold replica's first repeat query
        admits from the fleet's observations instead of the flat
        default. None when no fleet store is installed or it is blind."""
        st = self._fleet_store()
        if st is None:
            return None
        try:
            e = st.merged_admission(key)
        except Exception:
            return None
        if e is None:
            return None
        return max(int(e[0]), _MIN_EST_BYTES)

    def _record_history(self, key: Optional[str], result_bytes: int,
                        wall_us: int) -> None:
        if key is None or result_bytes < 0:
            return
        with self._hist_lock:
            e = self._fp_hist.get(key)
            if e is None:
                self._fp_hist[key] = (float(result_bytes),
                                      float(wall_us), 1)
            else:
                b, w, n = e
                self._fp_hist[key] = (
                    b + _HIST_ALPHA * (result_bytes - b),
                    w + _HIST_ALPHA * (wall_us - w), n + 1)
            while len(self._fp_hist) > _HIST_MAX_ENTRIES:
                self._fp_hist.pop(next(iter(self._fp_hist)))

    def _seed_history_from_flight(self) -> None:
        """One-time seed from flight-recorder records
        (``DAFT_TPU_QUERY_LOG``): serving blocks of past queries carry
        the history key + observed result bytes/latency, so a fresh
        process admits repeat queries from evidence immediately."""
        with self._hist_lock:
            if self._flight_seeded:
                return
            self._flight_seeded = True
        try:
            from .. import tracing
            entries = tracing.flight_history()
        except Exception:
            return
        for entry in reversed(entries):  # oldest-first into the EWMA
            sv = entry.get("serving")
            if not isinstance(sv, dict):
                continue
            key = sv.get("fp_hist_key")
            rb = sv.get("result_bytes")
            if key and isinstance(rb, (int, float)):
                self._record_history(str(key), int(rb),
                                     int(sv.get("run_us", 0) or 0))

    # ----------------------------------------------------------- dispatch
    def _pick_locked(self) -> Optional[QueryHandle]:
        best_prio = None
        for s in self._sessions.values():
            for prio, dq in s.queues.items():
                if dq and (best_prio is None or prio > best_prio):
                    best_prio = prio
        if best_prio is None:
            return None
        best_s = None
        for s in self._sessions.values():
            dq = s.queues.get(best_prio)
            if dq and (best_s is None or s.pass_ < best_s.pass_):
                best_s = s
        h = best_s.queues[best_prio].popleft()
        best_s.pass_ += 1.0 / best_s.weight
        self._n_queued -= 1
        return h

    def _sweep_expired_locked(self) -> None:
        now = time.monotonic()
        for s in self._sessions.values():
            for dq in s.queues.values():
                kept = [h for h in dq
                        if not self._expire_locked(h, now)]
                if len(kept) != len(dq):
                    dq.clear()
                    dq.extend(kept)
        self._n_queued = sum(s.depth() for s in self._sessions.values())
        # drop sessions that have sat empty past the idle TTL — session
        # names are client-minted (one UUID per Connect session), so an
        # unbounded dict here is a slow leak on the process-shared
        # scheduler and a linear cost on every dispatch
        drop = []
        for name, s in self._sessions.items():
            if s.depth() > 0:
                s.idle_since = None
            elif s.idle_since is None:
                s.idle_since = now
            elif now - s.idle_since > _SESSION_IDLE_TTL_S:
                drop.append(name)
        for name in drop:
            del self._sessions[name]

    def _expire_locked(self, h: QueryHandle, now: float) -> bool:
        if h.token.is_set():
            h._finish("cancelled")
            self._count("cancelled")
            self._cleanup(h)
            return True
        dl = self._deadlines.get(h)
        if dl is not None and now > dl:
            h._finish("rejected", error=AdmissionRejected(
                "queue_timeout",
                f"queued {now - h.submitted_at:.1f}s > queue timeout",
                waited_s=now - h.submitted_at))
            self._count("rejected_queue_timeout")
            self._cleanup(h)
            return True
        return False

    def _earliest_wait_locked(self) -> Optional[float]:
        dls = [self._deadlines[h]
               for s in self._sessions.values()
               for dq in s.queues.values() for h in dq
               if self._deadlines.get(h) is not None]
        if not dls:
            return None
        return max(min(dls) - time.monotonic(), 0.05)

    def _cleanup(self, h: QueryHandle) -> None:
        self._deadlines.pop(h, None)
        self._est.pop(h, None)
        self._builders.pop(h, None)

    def _cancel_queued(self, h: QueryHandle) -> None:
        with self._cond:
            for s in self._sessions.values():
                dq = s.queues.get(h.priority)
                if dq and h in dq:
                    dq.remove(h)
                    self._n_queued -= 1
                    h._finish("cancelled")
                    self._count("cancelled")
                    self._cleanup(h)
                    self._cond.notify_all()
                    return

    def _next(self):
        with self._cond:
            while True:
                if self._shutdown:
                    return None
                self._sweep_expired_locked()
                h = self._pick_locked()
                if h is not None:
                    est = self._est.pop(h, _DEFAULT_EST_BYTES)
                    builder = self._builders.pop(h, None)
                    self._deadlines.pop(h, None)
                    return h, est, builder
                self._cond.wait(self._earliest_wait_locked() or 1.0)

    def _sweep_loop(self) -> None:
        """Expire queued entries even when every worker is busy — a
        queue timeout must fire on time, not at the next dispatch."""
        with self._cond:
            while not self._shutdown:
                self._sweep_expired_locked()
                self._cond.wait(self._earliest_wait_locked() or 1.0)

    # -------------------------------------------------------------- worker
    def _worker_loop(self) -> None:
        while True:
            item = self._next()
            if item is None:
                return
            h, est, builder = item
            self._run_query(h, est, builder)

    def _run_query(self, h: QueryHandle, est: int, builder) -> None:
        from .. import observability as obs
        if h.token.is_set():
            h._finish("cancelled")
            self._count("cancelled")
            return
        budget = self.admission.budget
        if budget is not None and est > budget:
            h._finish("rejected", error=AdmissionRejected(
                "memory",
                f"estimated footprint {est} exceeds the serving "
                f"admission budget {budget}", est_bytes=est, budget=budget))
            self._count("rejected_memory")
            return
        # block in admission until the footprint fits; the queue deadline
        # already elapsed into queue wait, so bound this by the same
        # timeout from NOW (a query admitted late should still run)
        adm_deadline = time.monotonic() + self.queue_timeout_s \
            if self.queue_timeout_s and self.queue_timeout_s > 0 else None
        if not self.admission.try_acquire(est, adm_deadline, h.token):
            if h.token.is_set():
                h._finish("cancelled")
                self._count("cancelled")
            else:
                h._finish("rejected", error=AdmissionRejected(
                    "queue_timeout",
                    f"admission wait exceeded the queue timeout "
                    f"({self.queue_timeout_s}s) for {est} bytes",
                    est_bytes=est, budget=budget,
                    waited_s=time.monotonic() - h.submitted_at))
                self._count("rejected_queue_timeout")
            return
        # EVERYTHING after a successful try_acquire runs under the
        # try/finally that releases it — the run-state bump, the handle
        # transition and the queue-wait span emission all make calls, and
        # an exception on any of them used to leak the admitted bytes
        # (and a worker slot: _n_running never decremented) for the
        # process lifetime. Found by daft-lint's memory-admission-leak
        # flow check.
        queue_wait_us = 0
        running = False
        try:
            with self._cond:
                self._n_running += 1
                self._running.add(h)
                running_at_admit = self._n_running
            running = True
            h._mark_running()
            queue_wait_us = int(h.queue_wait_s * 1e6)
            from .. import tracing
            if h.trace_ctx is not None:
                # the queue-wait span: submit → run start, on the timeline
                rec = h.trace_ctx.recorder
                rec.add("serve:queue", rec.unique_span_id("serve:queue"),
                        h.trace_ctx.span_id, h.submitted_at_us,
                        queue_wait_us,
                        attrs={"session": h.session,
                               "priority": h.priority,
                               "admitted_bytes": est},
                        lane="serving")
            # nested scope: the executor's set_last_stats must not fire
            # the per-query exports mid-flight — the serving info isn't
            # attached yet; finalize_query below is the single exporter
            with cancel_scope(h.token), obs.nested_scope(), \
                    tracing.attach(h.trace_ctx), \
                    tracing.span("serve:run", lane="serving"):
                ps, stats, info = self._execute(h, builder)
            info.update({
                "session": h.session, "priority": h.priority,
                "queue_wait_us": queue_wait_us, "admitted_bytes": est,
                "running_at_admit": running_at_admit})
            if h._fp_hist_key is not None:
                # close the admission loop: the OBSERVED result bytes +
                # wall feed the per-fingerprint history (and ride the
                # flight-recorder serving block for future processes)
                try:
                    result_bytes = int(ps.size_bytes()) \
                        if ps is not None else 0
                except Exception:
                    result_bytes = 0
                run_us = int((time.monotonic()
                              - (h.started_at or h.submitted_at)) * 1e6)
                self._record_history(h._fp_hist_key, result_bytes,
                                     run_us)
                info.update({"fp_hist_key": h._fp_hist_key,
                             "result_bytes": result_bytes,
                             "run_us": run_us})
            if stats is None:
                # result-cache hit: no execution happened — synthesize an
                # (attributed, hence plane-empty) context so
                # explain(analyze=True) still renders the serving block
                stats = obs.RuntimeStatsContext()
                stats.trace_ctx = h.trace_ctx
                stats._attributed = True
                stats.finish()
            stats.serving = info
            # finalize BEFORE completing the handle: a result() waiter
            # must be able to read the exported trace / flight record
            obs.finalize_query(stats)
            h._finish("done", result=ps, stats=stats)
            self._count("completed")
            self._count("queue_wait_us", queue_wait_us)
            self._count("run_us", int((time.monotonic()
                                       - (h.started_at or 0)) * 1e6))
        except QueryCancelled:
            h._finish("cancelled")
            self._count("cancelled")
        except BaseException as exc:  # noqa: BLE001 — surfaced via handle
            # a failed query is the one an operator most needs to see:
            # export its trace (error status) + flight-recorder entry
            # BEFORE completing the handle (result() waiters may read it)
            try:
                stats = obs.RuntimeStatsContext()
                stats.trace_ctx = h.trace_ctx
                stats._attributed = True
                stats.finish()
                stats.serving = {
                    "session": h.session, "priority": h.priority,
                    "queue_wait_us": queue_wait_us,
                    "admitted_bytes": est, "state": "failed",
                    "error": f"{type(exc).__name__}: {str(exc)[:200]}"}
                if h.trace_ctx is not None:
                    h.trace_ctx.recorder.status = "error"
                obs.finalize_query(stats)
            except Exception:
                pass  # export must never mask the query's real failure
            h._finish("failed", error=exc)
            self._count("failed")
        finally:
            self.admission.release(est)
            with self._cond:
                if running:
                    self._n_running -= 1
                self._running.discard(h)
                self._cond.notify_all()

    # ------------------------------------------------------------- execute
    def _execute(self, h: QueryHandle, builder):
        from .. import observability as obs
        from .. import tracing
        from ..context import get_context
        from ..logical.fingerprint import fingerprint
        from ..physical.translate import translate
        from ..runners.native_runner import NativeRunner, make_local_executor
        from ..runners.runner import PartitionSet

        ctx = get_context()
        runner = ctx.get_or_create_runner()
        cfg = ctx.execution_config
        info: Dict[str, object] = {"plan_cache": "bypass",
                                   "result_cache": "bypass"}
        cacheable = isinstance(runner, NativeRunner) \
            and not cfg.enable_aqe
        with tracing.span("plan:fingerprint", lane="planner"):
            fp = fingerprint(builder.plan, cfg) if cacheable else None
        tier = self._fleet_cache_tier()
        if fp is not None and self.result_cache.enabled:
            ps = self.result_cache.get_result(fp)
            if ps is not None:
                info["result_cache"] = "hit"
                info["plan_cache"] = "skipped"
                tracing.event("cache:result_hit", lane="planner")
                return ps, None, info
            if tier is not None:
                # local miss → the fleet tier: a repeat query that last
                # ran on a peer replica still hits warm state. The tier
                # degrades to a miss on any failure; a hit is promoted
                # into the local cache so the next repeat is local.
                try:
                    ps = tier.get_result(fp)
                except Exception:
                    ps = None
                if ps is not None:
                    info["result_cache"] = "fleet_hit"
                    info["plan_cache"] = "skipped"
                    self._count("result_cache_fleet_hits")
                    tracing.event("cache:result_fleet_hit", lane="planner")
                    self.result_cache.put_result(fp, ps)
                    return ps, None, info
                self._count("result_cache_fleet_misses")
            info["result_cache"] = "miss"
        if not cacheable:
            # AQE / distributed runner: the scheduler still provides
            # fairness + admission; plan shape is dynamic, caches bypass.
            # These runners don't thread the CancelToken into their own
            # workers, so check it at every partition boundary here —
            # INTERRUPT must unwind (and release admission) between
            # stages, not silently run the query to completion
            parts = []
            for p in runner.run_iter(builder):
                h.token.check()
                parts.append(p)
            return (PartitionSet(parts, builder.schema()),
                    obs.last_query_stats_local(), info)
        if fp is not None and h._fp_hist_key is None:
            # every EXECUTED cacheable query feeds the per-fingerprint
            # admission history, not just blind-estimate ones (cache
            # hits returned above — their ~0 wall would pollute the
            # EWMA): warm replicas publish observed bytes/wall to the
            # fleet store, which is what a cold replica's blind
            # estimates seed from
            h._fp_hist_key = _history_key_from_fp(fp)
        hit = self.plan_cache.get_plan(fp) if self.plan_cache.enabled \
            else None
        if hit is not None:
            _optimized, pplan = hit
            info["plan_cache"] = "hit"
            tracing.event("cache:plan_hit", lane="planner")
        else:
            tiered = None
            if fp is not None and self.plan_cache.enabled \
                    and tier is not None:
                try:
                    tiered = tier.get_plan(fp)
                except Exception:
                    tiered = None
            if tiered is not None:
                optimized_plan, pplan = tiered
                info["plan_cache"] = "fleet_hit"
                self._count("plan_cache_fleet_hits")
                tracing.event("cache:plan_fleet_hit", lane="planner")
                self.plan_cache.put_plan(fp, optimized_plan, pplan)
            else:
                with tracing.span("plan:optimize", lane="planner"):
                    optimized = builder.optimize()
                with tracing.span("plan:translate", lane="planner"):
                    pplan = translate(optimized.plan)
                if fp is not None and self.plan_cache.enabled:
                    self.plan_cache.put_plan(fp, optimized.plan, pplan)
                    if tier is not None:
                        try:
                            tier.put_plan(fp, optimized.plan, pplan)
                        except Exception:
                            pass
                    info["plan_cache"] = "miss"
        executor = make_local_executor(cfg)
        parts = list(executor.run(pplan))
        stats = obs.last_query_stats_local()
        ps = PartitionSet(parts, builder.schema())
        if fp is not None and self.result_cache.enabled:
            self.result_cache.put_result(fp, ps)
            if tier is not None:
                try:
                    tier.put_result(fp, ps)
                except Exception:
                    pass
        return ps, stats, info

    # ------------------------------------------------------------ shutdown
    def shutdown(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._shutdown = True
            for s in self._sessions.values():
                for dq in s.queues.values():
                    for h in dq:
                        h._finish("rejected", error=AdmissionRejected(
                            "shutdown", "scheduler shut down while queued"))
                        self._count("rejected_shutdown")
                    dq.clear()
            self._n_queued = 0
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)
