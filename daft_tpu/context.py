"""Global context: config + runner singleton.

Reference: ``src/daft-context/src/lib.rs`` (runner transitions),
``src/common/daft-config/src/lib.rs:40-100`` (the two frozen config objects
and their ~26 knobs), ``daft/context.py:156-269`` (python surface).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
from typing import Any, Dict, Optional


@dataclasses.dataclass(frozen=True)
class PlanningConfig:
    default_io_config: Optional[Any] = None


@dataclasses.dataclass(frozen=True)
class ExecutionConfig:
    """Frozen-per-query execution knobs (reference defaults at
    ``src/common/daft-config/src/lib.rs:70-100``)."""

    scan_tasks_min_size_bytes: int = 96 * 1024 * 1024
    scan_tasks_max_size_bytes: int = 384 * 1024 * 1024
    max_sources_per_scan_task: int = 10
    broadcast_join_size_bytes_threshold: int = 10 * 1024 * 1024
    sort_merge_join_sort_with_aligned_boundaries: bool = False
    hash_join_partition_size_leniency: float = 0.5
    sample_size_for_sort: int = 20
    parquet_split_row_groups_max_files: int = 10
    num_preview_rows: int = 8
    parquet_target_filesize: int = 512 * 1024 * 1024
    parquet_target_row_group_size: int = 128 * 1024 * 1024
    parquet_inflation_factor: float = 3.0
    csv_target_filesize: int = 512 * 1024 * 1024
    csv_inflation_factor: float = 0.5
    shuffle_aggregation_default_partitions: int = 200
    partial_aggregation_threshold: int = 10000
    high_cardinality_aggregation_threshold: float = 0.8
    read_sql_partition_size_bytes: int = 512 * 1024 * 1024
    enable_aqe: bool = False
    default_morsel_size: int = 128 * 1024
    min_cpu_per_task: float = 1.0
    enable_ray_tracing: bool = False
    flight_shuffle_dirs: tuple = ("/tmp",)
    # local hash-exchange strategy (reference: the 4 ShuffleExchange
    # strategies, ops/shuffle_exchange.rs:41-58): "naive" materializes the
    # child then fans out; "spill_cache" streams morsels through a
    # per-partition spill cache (the FlightShuffle/pre-merge design — map
    # outputs accumulate merged per partition, never holding the child);
    # "auto" picks spill_cache when a memory limit is set
    shuffle_algorithm: str = "auto"
    # local engine: "push" = per-operator workers over bounded channels
    # (execution/pipeline.py, the reference's Swordfish dataflow); "interp"
    # = the pull-generator interpreter (execution/executor.py alone)
    local_executor: str = "push"
    # TPU-specific knobs
    device_min_rows: int = 0
    device_enabled: bool = True
    # async device pipeline (round 17, device/pipeline.py): in-flight
    # morsel slots; env override spells the documented knob
    # (DAFT_TPU_DEVICE_INFLIGHT); 0 = synchronous dispatch
    tpu_device_inflight: int = 2
    target_partition_size_bytes: int = 512 * 1024 * 1024
    # shape discipline (round 16): the size-class ladder batches pad to
    # (DAFT_TPU_SIZE_CLASSES) and the AOT warm-up toggle
    # (DAFT_TPU_AOT_WARMUP) — env spellings match the documented knobs
    tpu_size_classes: str = "pow2"
    tpu_aot_warmup: bool = False
    # scan fast path (io/read_planner.py). Field names are chosen so the
    # DAFT_<NAME> env override spells the documented knob names
    # (DAFT_TPU_IO_COALESCE_GAP, DAFT_TPU_SCAN_PREFETCH, …); byte values
    # accept suffixes ("1MiB") via the env parser below.
    tpu_io_coalesce_gap: int = 1 << 20       # range-coalescing hole tolerance
    tpu_io_min_request: int = 8 << 20        # coalesced-request size floor
    tpu_io_range_parallelism: int = 8        # concurrent range GETs / source
    tpu_io_planned_reads: bool = True        # 0 → naive per-chunk ranged GETs
    tpu_scan_prefetch: int = 2               # ScanTasks resolved ahead
    # pod-native shuffle (distributed/topology.py): which workers share a
    # device mesh, and the hash-boundary exchange path. Field names spell
    # the documented knobs (DAFT_TPU_WORKER_TOPOLOGY /
    # DAFT_TPU_EXCHANGE_PATH); the env var is the per-process override.
    tpu_worker_topology: str = ""            # "" → autodetect
    tpu_exchange_path: str = "auto"          # collective|hierarchical|flight
    # out-of-core execution (execution/out_of_core.py): grace hash join
    # and spill-partitioned aggregation gates. Field names spell the
    # documented knobs (DAFT_TPU_SPILL_JOIN, …); env is the per-process
    # override.
    tpu_spill_join: str = "auto"             # auto|1 (force)|0 (legacy)
    tpu_spill_agg: str = "auto"              # auto|1 (force)|0 (decline)
    tpu_spill_partitions: int = 0            # 0 → planner evidence decides
    tpu_spill_max_depth: int = 3             # rotated-radix recursion bound
    # spill-plane fast path + memory governor (round 23,
    # execution/spill_io.py / execution/governor.py). Field names spell
    # the documented knobs (DAFT_TPU_SPILL_COMPRESSION, …); env is the
    # per-process override.
    tpu_spill_compression: str = ""          # ""→inherit shuffle codec
    tpu_spill_io_parallelism: int = 4        # 0 → serial r19 write path
    tpu_governor_high: float = 0.85          # pressured above this × limit
    tpu_governor_low: float = 0.70           # …until RSS falls below this
    # self-tuning feedback loops (round 20): distributed runtime
    # re-planning (distributed/replan.py) and the calibrated cost-model
    # profile (device/calibration.py). Field names spell the documented
    # knobs (DAFT_TPU_ADAPTIVE, DAFT_TPU_CALIBRATION, …); the env var is
    # the per-process override.
    # whole-query fusion regions (round 21, physical/fusion.py): the
    # planner grows maximal device-eligible operator chains into single
    # donated-buffer XLA programs. Field names spell the documented knobs
    # (DAFT_TPU_FUSION / DAFT_TPU_FUSION_MAX_OPS); env is the per-process
    # override.
    tpu_fusion: str = "auto"                 # auto|1 (force)|0 (off)
    tpu_fusion_max_ops: int = 8              # region-size cap (fused ops)
    tpu_adaptive: bool = False               # runtime re-planning
    tpu_adaptive_history: int = 512          # AdaptivePlanner history cap
    tpu_calibration: bool = False            # learned cost-model profile
    tpu_calibration_dir: str = ""            # "" → in-memory only
    tpu_calibration_alpha: float = 0.2       # EWMA observation weight
    tpu_calibration_min_samples: int = 8     # floor before overriding
    # serving plane (serving/scheduler.py); env spellings match the
    # documented serve knobs (DAFT_TPU_SERVE_CONCURRENCY, …)
    tpu_serve_concurrency: int = 4           # scheduler worker slots
    tpu_serve_queue_depth: int = 64          # queued-query cap
    tpu_serve_queue_timeout: float = 30.0    # queue+admission wait bound (s)
    tpu_serve_plan_cache_bytes: int = 64 << 20    # compiled-plan LRU budget
    tpu_serve_result_cache_bytes: int = 64 << 20  # result LRU budget
    # serving fleet (fleet/); env spellings match the documented fleet
    # knobs (DAFT_TPU_FLEET_VNODES, …)
    tpu_fleet_vnodes: int = 64               # ring vnodes per replica
    tpu_fleet_gossip_s: float = 2.0          # gossip round interval (s)
    tpu_fleet_drain_timeout: float = 10.0    # drain grace before cancel (s)
    # plan discipline (round 22, analysis/plan_sanitizer.py /
    # analysis/plan_fuzzer.py); env spellings match the documented knobs
    # (DAFT_TPU_SANITIZE_PLAN, DAFT_TPU_FUZZ_SEED, …)
    tpu_sanitize_plan: bool = False          # runtime plan sanitizer
    tpu_sanitize_plan_sample: int = 64       # rows sampled per boundary
    tpu_fuzz_seed: int = 0                   # differential fuzzer base seed
    tpu_fuzz_count: int = 50                 # differential fuzzer seed count


def _exec_config_from_env() -> ExecutionConfig:
    kwargs: Dict[str, Any] = {}
    for f in dataclasses.fields(ExecutionConfig):
        env = os.environ.get(f"DAFT_{f.name.upper()}")
        if env is not None:
            if f.type == "bool" or isinstance(f.default, bool):
                kwargs[f.name] = env not in ("0", "false", "False")
            elif isinstance(f.default, int):
                try:
                    kwargs[f.name] = int(env)
                except ValueError:
                    # byte knobs accept suffixed values ("1MiB", "8MB")
                    from .execution.memory import parse_bytes
                    kwargs[f.name] = parse_bytes(env)
            elif isinstance(f.default, float):
                kwargs[f.name] = float(env)
            elif isinstance(f.default, str):
                kwargs[f.name] = env
    return ExecutionConfig(**kwargs)


class Context:
    def __init__(self):
        self._lock = threading.RLock()
        self._runner = None
        self.planning_config = PlanningConfig()
        self.execution_config = _exec_config_from_env()

    def get_or_create_runner(self):
        with self._lock:
            if self._runner is None:
                name = os.environ.get("DAFT_RUNNER", "native").lower()
                if name in ("native", "py"):
                    from .runners.native_runner import NativeRunner
                    self._runner = NativeRunner()
                elif name in ("tpu_distributed", "distributed"):
                    from .runners.distributed_runner import DistributedRunner
                    self._runner = DistributedRunner()
                else:
                    raise ValueError(f"unknown DAFT_RUNNER {name!r}")
            return self._runner

    def set_runner(self, runner):
        with self._lock:
            self._runner = runner


_context: Optional[Context] = None
_context_lock = threading.Lock()


def get_context() -> Context:
    global _context
    with _context_lock:
        if _context is None:
            _context = Context()
        return _context


def set_runner_native() -> Context:
    ctx = get_context()
    from .runners.native_runner import NativeRunner
    ctx.set_runner(NativeRunner())
    return ctx


def set_runner_tpu_distributed(num_workers: Optional[int] = None) -> Context:
    ctx = get_context()
    from .runners.distributed_runner import DistributedRunner
    ctx.set_runner(DistributedRunner(num_workers=num_workers))
    return ctx


def set_execution_config(config: Optional[ExecutionConfig] = None, **kwargs) -> Context:
    ctx = get_context()
    base = config or ctx.execution_config
    ctx.execution_config = dataclasses.replace(base, **kwargs)
    return ctx


def set_planning_config(config: Optional[PlanningConfig] = None, **kwargs) -> Context:
    ctx = get_context()
    base = config or ctx.planning_config
    ctx.planning_config = dataclasses.replace(base, **kwargs)
    return ctx


@contextlib.contextmanager
def execution_config_ctx(**kwargs):
    ctx = get_context()
    old = ctx.execution_config
    try:
        ctx.execution_config = dataclasses.replace(old, **kwargs)
        yield ctx
    finally:
        ctx.execution_config = old


@contextlib.contextmanager
def planning_config_ctx(**kwargs):
    ctx = get_context()
    old = ctx.planning_config
    try:
        ctx.planning_config = dataclasses.replace(old, **kwargs)
        yield ctx
    finally:
        ctx.planning_config = old
