"""Process actor pools for stateful UDFs.

Reference mechanism: ``daft/execution/actor_pool_udf.py:22-114`` — each
stateful UDF gets a pool of OS processes holding one instance each, with
batches shipped over IPC, so N-way concurrency runs N real interpreters
(no GIL sharing, true per-actor state). Here transport is Arrow IPC over
``multiprocessing`` pipes; the UDF class and init args ship once at spawn.

Falls back transparently to the in-process shared instance when the UDF
isn't picklable (e.g. defined in a REPL closure) or when
``DAFT_TPU_ACTOR_POOL=0``.
"""

from __future__ import annotations

import io
import os
import pickle
import queue
import threading
import traceback
import weakref
from typing import Any, List, Optional, Tuple

import pyarrow as pa


def _series_to_ipc(series_list) -> bytes:
    import pyarrow.ipc as paipc
    arrays = []
    names = []
    for i, s in enumerate(series_list):
        arr = s.to_arrow()
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        arrays.append(arr)
        names.append(f"c{i}")
    # lengths can differ (scalar args); ship each column as its own batch
    sink = io.BytesIO()
    meta = []
    for name, arr in zip(names, arrays):
        t = pa.table({name: arr})
        w = paipc.new_stream(sink, t.schema)
        w.write_table(t)
        w.close()
        meta.append(sink.tell())
    return pickle.dumps((meta, sink.getvalue()))


def _series_from_ipc(blob: bytes):
    import pyarrow.ipc as paipc
    from .series import Series
    meta, payload = pickle.loads(blob)
    out = []
    start = 0
    for end in meta:
        rdr = paipc.open_stream(pa.BufferReader(payload[start:end]))
        t = rdr.read_all()
        out.append(Series.from_arrow(t.column(0), t.column_names[0]))
        start = end
    return out


def _loads_udf(blob: bytes):
    try:
        import cloudpickle
        return cloudpickle.loads(blob)
    except ImportError:
        return pickle.loads(blob)


def _dumps_udf(obj) -> bytes:
    # classes decorated by @udf are shadowed by the UDF wrapper at module
    # scope, so by-reference pickling can't resolve them — serialize by
    # value (the reference vendors cloudpickle for exactly this)
    try:
        import cloudpickle
        return cloudpickle.dumps(obj)
    except ImportError:
        return pickle.dumps(obj)


def _actor_main(conn, udf_blob: bytes) -> None:
    """Child process: instantiate once, serve call messages forever."""
    try:
        cls, init_args, return_dtype, batch_size, name = _loads_udf(udf_blob)
        a, kw = init_args or ((), {})
        instance = cls(*a, **kw)
        conn.send(("ready", None))
    except Exception:
        conn.send(("err", traceback.format_exc()))
        return
    from .udf import run_udf_batches
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            return
        if msg is None or msg[0] == "stop":
            return
        try:
            _, ipc_in, arg_spec, kw_spec, length = msg
            evaluated = _series_from_ipc(ipc_in)
            out = run_udf_batches(instance, evaluated, arg_spec, kw_spec,
                                  length, batch_size, return_dtype, name)
            conn.send(("ok", _series_to_ipc([out])))
        except Exception:
            conn.send(("err", traceback.format_exc()))


def _stop_actors(actors) -> None:
    for a in actors:
        a.stop()


class _Actor:
    def __init__(self, udf_blob: bytes):
        import multiprocessing as mp
        ctx = mp.get_context("spawn")
        self._parent, child = ctx.Pipe()
        self.process = ctx.Process(target=_actor_main, args=(child, udf_blob),
                                   daemon=True)
        self.process.start()
        child.close()
        kind, detail = self._parent.recv()
        if kind != "ready":
            raise RuntimeError(f"actor failed to initialize:\n{detail}")

    def call(self, evaluated, arg_spec, kw_spec, length):
        self._parent.send(("call", _series_to_ipc(evaluated), arg_spec,
                           kw_spec, length))
        kind, payload = self._parent.recv()
        if kind != "ok":
            raise RuntimeError(f"actor UDF raised:\n{payload}")
        return _series_from_ipc(payload)[0]

    def stop(self):
        try:
            self._parent.send(("stop",))
        except (OSError, BrokenPipeError):
            pass
        self.process.join(timeout=2)
        if self.process.is_alive():
            self.process.terminate()


class ActorPool:
    """N OS-process actors; calls check out an idle actor (blocking when all
    are busy), giving concurrency == pool size."""

    def __init__(self, udf, size: int):
        blob = _dumps_udf((udf.func, udf.init_args, udf.return_dtype,
                           udf.batch_size, udf.name))
        self._actors = [_Actor(blob) for _ in range(max(size, 1))]
        self._idle: "queue.Queue[_Actor]" = queue.Queue()
        for a in self._actors:
            self._idle.put(a)
        # finalize (not atexit): a discarded pool's workers stop when the
        # pool is garbage-collected, not at process exit
        self._finalizer = weakref.finalize(self, _stop_actors, self._actors)

    @property
    def size(self) -> int:
        return len(self._actors)

    def call(self, evaluated, arg_spec, kw_spec, length):
        actor = self._idle.get()
        try:
            return actor.call(evaluated, arg_spec, kw_spec, length)
        finally:
            self._idle.put(actor)

    def shutdown(self):
        self._finalizer()


def pool_enabled() -> bool:
    from .analysis import knobs
    return knobs.env_bool("DAFT_TPU_ACTOR_POOL")


def try_make_pool(udf) -> Optional[ActorPool]:
    """Build a pool for a stateful UDF, or None when the UDF can't ship
    across a process boundary (falls back to the shared instance)."""
    if not pool_enabled():
        return None
    try:
        _dumps_udf((udf.func, udf.init_args))
    except Exception:
        return None
    try:
        return ActorPool(udf, udf.concurrency or 1)
    except Exception:
        return None
