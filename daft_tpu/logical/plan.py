"""Logical plan nodes.

Reference: ``src/daft-logical-plan/src/logical_plan.rs:25`` — the LogicalPlan
enum (Source/Project/Filter/Limit/Explode/Unpivot/Sort/Repartition/Distinct/
Aggregate/Pivot/Concat/Join/Sink/Sample/MonotonicallyIncreasingId/Window/TopN)
— and ``partitioning.rs`` (ClusteringSpec).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

from ..expressions import Expression, col
from ..expressions.typing import supertype
from ..schema import Field, Schema


@dataclasses.dataclass(frozen=True)
class ClusteringSpec:
    """How rows are distributed across partitions."""

    kind: str = "unknown"            # hash | range | random | unknown
    num_partitions: int = 1
    by: Tuple[Expression, ...] = ()
    descending: Tuple[bool, ...] = ()

    def normalized(self) -> "ClusteringSpec":
        return self


class LogicalPlan:
    """Base node; immutable tree."""

    def __init__(self, children: List["LogicalPlan"]):
        self._children = children
        self._schema: Optional[Schema] = None

    @property
    def children(self) -> List["LogicalPlan"]:
        return self._children

    def schema(self) -> Schema:
        if self._schema is None:
            self._schema = self._compute_schema()
        return self._schema

    def _compute_schema(self) -> Schema:
        raise NotImplementedError

    def with_children(self, children: List["LogicalPlan"]) -> "LogicalPlan":
        raise NotImplementedError

    def clustering_spec(self) -> ClusteringSpec:
        if self._children:
            return self._children[0].clustering_spec()
        return ClusteringSpec()

    def num_partitions(self) -> int:
        return self.clustering_spec().num_partitions

    def name(self) -> str:
        return type(self).__name__

    def multiline_display(self) -> List[str]:
        return [self.name()]

    # generic tree utilities -------------------------------------------
    def transform_up(self, fn) -> "LogicalPlan":
        new_children = [c.transform_up(fn) for c in self._children]
        node = self if new_children == self._children \
            else self.with_children(new_children)
        return fn(node)

    def transform_down(self, fn) -> "LogicalPlan":
        node = fn(self)
        new_children = [c.transform_down(fn) for c in node.children]
        return node if new_children == node.children \
            else node.with_children(new_children)

    def semantic_id(self) -> Tuple:
        return (self.name(),
                tuple(repr(x) for x in self._params()),
                tuple(c.semantic_id() for c in self._children))

    def _params(self) -> Tuple:
        return ()

    def repr_ascii(self, depth: int = 0) -> str:
        pad = "  " * depth
        lines = [pad + ("* " if depth == 0 else "|- ") +
                 "\n  ".join(self.multiline_display())]
        for c in self._children:
            lines.append(c.repr_ascii(depth + 1))
        return "\n".join(lines)


class Source(LogicalPlan):
    def __init__(self, scan_op=None, partitions=None, schema: Schema = None,
                 pushdowns=None, num_partitions: int = 1):
        super().__init__([])
        from ..io.scan import Pushdowns
        self.scan_op = scan_op
        self.partitions = partitions   # list[MicroPartition] for in-memory
        self._source_schema = schema
        self.pushdowns = pushdowns or Pushdowns()
        self._num_partitions = num_partitions

    def _compute_schema(self) -> Schema:
        base = self._source_schema
        if self.pushdowns.columns is not None:
            return base.project([c for c in self.pushdowns.columns if c in base])
        return base

    def with_children(self, children):
        assert not children
        return self

    def with_pushdowns(self, pushdowns) -> "Source":
        return Source(self.scan_op, self.partitions, self._source_schema,
                      pushdowns, self._num_partitions)

    def clustering_spec(self) -> ClusteringSpec:
        if self.partitions is not None:
            return ClusteringSpec("unknown", max(len(self.partitions), 1))
        if self.scan_op is not None:
            # partition count = materialized scan-task count, sharing the
            # same cache execution/translate use so footers are read once
            tasks = getattr(self, "materialized_tasks", None)
            if tasks is None:
                try:
                    tasks = self.scan_op.to_scan_tasks(self.pushdowns)
                    self.materialized_tasks = tasks
                except Exception:
                    return ClusteringSpec("unknown", self._num_partitions)
            return ClusteringSpec("unknown", max(len(tasks), 1))
        return ClusteringSpec("unknown", self._num_partitions)

    def _params(self):
        return (id(self.scan_op) if self.scan_op else id(self.partitions),
                self.pushdowns)

    def multiline_display(self):
        src = "InMemory" if self.partitions is not None else \
            type(self.scan_op).__name__
        out = [f"Source [{src}]", f"schema = {self.schema().column_names}"]
        if self.pushdowns.filters is not None:
            out.append(f"filter pushdown = {self.pushdowns.filters!r}")
        if self.pushdowns.limit is not None:
            out.append(f"limit pushdown = {self.pushdowns.limit}")
        return out


class Project(LogicalPlan):
    def __init__(self, child: LogicalPlan, exprs: List[Expression]):
        super().__init__([child])
        self.exprs = list(exprs)

    def _compute_schema(self) -> Schema:
        s = self._children[0].schema()
        return Schema([e.to_field(s) for e in self.exprs])

    def with_children(self, children):
        return Project(children[0], self.exprs)

    def _params(self):
        return tuple(e._key() for e in self.exprs)

    def multiline_display(self):
        return [f"Project: {', '.join(repr(e) for e in self.exprs[:6])}"
                + ("…" if len(self.exprs) > 6 else "")]


class UDFProject(LogicalPlan):
    """Projection containing a stateful/actor UDF, isolated so the executor
    can give it its own worker pool (reference: ActorPoolProject)."""

    def __init__(self, child: LogicalPlan, exprs: List[Expression],
                 concurrency: Optional[int] = None):
        super().__init__([child])
        self.exprs = list(exprs)
        self.concurrency = concurrency

    def _compute_schema(self) -> Schema:
        s = self._children[0].schema()
        return Schema([e.to_field(s) for e in self.exprs])

    def with_children(self, children):
        return UDFProject(children[0], self.exprs, self.concurrency)

    def _params(self):
        return tuple(e._key() for e in self.exprs)


class Filter(LogicalPlan):
    def __init__(self, child: LogicalPlan, predicate: Expression):
        super().__init__([child])
        self.predicate = predicate

    def _compute_schema(self) -> Schema:
        return self._children[0].schema()

    def with_children(self, children):
        return Filter(children[0], self.predicate)

    def _params(self):
        return (self.predicate._key(),)

    def multiline_display(self):
        return [f"Filter: {self.predicate!r}"]


class Limit(LogicalPlan):
    def __init__(self, child: LogicalPlan, limit: int, offset: int = 0):
        super().__init__([child])
        self.limit = limit
        self.offset = offset

    def _compute_schema(self) -> Schema:
        return self._children[0].schema()

    def with_children(self, children):
        return Limit(children[0], self.limit, self.offset)

    def _params(self):
        return (self.limit, self.offset)

    def multiline_display(self):
        return [f"Limit: {self.limit}"]


class Explode(LogicalPlan):
    def __init__(self, child: LogicalPlan, exprs: List[Expression]):
        super().__init__([child])
        self.exprs = list(exprs)

    def _compute_schema(self) -> Schema:
        s = self._children[0].schema()
        out = []
        explode_names = {e.name(): e for e in self.exprs}
        for f in s:
            if f.name in explode_names:
                out.append(explode_names[f.name].to_field(s))
            else:
                out.append(f)
        for e in self.exprs:
            if e.name() not in s:
                out.append(e.to_field(s))
        return Schema(out)

    def with_children(self, children):
        return Explode(children[0], self.exprs)

    def _params(self):
        return tuple(e._key() for e in self.exprs)


class Unpivot(LogicalPlan):
    def __init__(self, child, ids, values, variable_name, value_name):
        super().__init__([child])
        self.ids = list(ids)
        self.values = list(values)
        self.variable_name = variable_name
        self.value_name = value_name

    def _compute_schema(self) -> Schema:
        from ..datatype import DataType
        s = self._children[0].schema()
        fields = [e.to_field(s) for e in self.ids]
        vdt = None
        for e in self.values:
            d = e.to_field(s).dtype
            vdt = d if vdt is None else supertype(vdt, d)
        fields.append(Field(self.variable_name, DataType.string()))
        fields.append(Field(self.value_name, vdt))
        return Schema(fields)

    def with_children(self, children):
        return Unpivot(children[0], self.ids, self.values,
                       self.variable_name, self.value_name)

    def _params(self):
        return (tuple(e._key() for e in self.ids),
                tuple(e._key() for e in self.values),
                self.variable_name, self.value_name)


class Sort(LogicalPlan):
    def __init__(self, child, sort_by, descending, nulls_first):
        super().__init__([child])
        self.sort_by = list(sort_by)
        self.descending = list(descending)
        self.nulls_first = list(nulls_first)

    def _compute_schema(self) -> Schema:
        return self._children[0].schema()

    def with_children(self, children):
        return Sort(children[0], self.sort_by, self.descending, self.nulls_first)

    def clustering_spec(self) -> ClusteringSpec:
        return ClusteringSpec("range", self._children[0].num_partitions(),
                              tuple(self.sort_by), tuple(self.descending))

    def _params(self):
        return (tuple(e._key() for e in self.sort_by),
                tuple(self.descending), tuple(self.nulls_first))

    def multiline_display(self):
        return [f"Sort: {', '.join(repr(e) for e in self.sort_by)}"]


class TopN(LogicalPlan):
    def __init__(self, child, sort_by, descending, nulls_first, limit: int):
        super().__init__([child])
        self.sort_by = list(sort_by)
        self.descending = list(descending)
        self.nulls_first = list(nulls_first)
        self.limit = limit

    def _compute_schema(self) -> Schema:
        return self._children[0].schema()

    def with_children(self, children):
        return TopN(children[0], self.sort_by, self.descending,
                    self.nulls_first, self.limit)

    def _params(self):
        return (tuple(e._key() for e in self.sort_by), tuple(self.descending),
                tuple(self.nulls_first), self.limit)


class Repartition(LogicalPlan):
    def __init__(self, child, spec: ClusteringSpec):
        super().__init__([child])
        self.spec = spec

    def _compute_schema(self) -> Schema:
        return self._children[0].schema()

    def with_children(self, children):
        return Repartition(children[0], self.spec)

    def clustering_spec(self) -> ClusteringSpec:
        return self.spec

    def _params(self):
        return (self.spec.kind, self.spec.num_partitions,
                tuple(e._key() for e in self.spec.by))

    def multiline_display(self):
        return [f"Repartition[{self.spec.kind}] n={self.spec.num_partitions}"]


class Distinct(LogicalPlan):
    def __init__(self, child, on: Optional[List[Expression]] = None):
        super().__init__([child])
        self.on = on

    def _compute_schema(self) -> Schema:
        return self._children[0].schema()

    def with_children(self, children):
        return Distinct(children[0], self.on)

    def _params(self):
        return tuple(e._key() for e in (self.on or []))


class Aggregate(LogicalPlan):
    def __init__(self, child, aggs: List[Expression],
                 group_by: List[Expression]):
        super().__init__([child])
        self.aggs = list(aggs)
        self.group_by = list(group_by)

    def _compute_schema(self) -> Schema:
        s = self._children[0].schema()
        fields = [e.to_field(s) for e in self.group_by]
        fields += [e.to_field(s) for e in self.aggs]
        return Schema(fields)

    def with_children(self, children):
        return Aggregate(children[0], self.aggs, self.group_by)

    def _params(self):
        return (tuple(e._key() for e in self.aggs),
                tuple(e._key() for e in self.group_by))

    def multiline_display(self):
        return [f"Aggregate: {', '.join(repr(a) for a in self.aggs[:4])}",
                f"group_by = {[repr(g) for g in self.group_by]}"]


class Pivot(LogicalPlan):
    def __init__(self, child, group_by, pivot_col, value_col, agg_expr, names):
        super().__init__([child])
        self.group_by = list(group_by)
        self.pivot_col = pivot_col
        self.value_col = value_col
        self.agg_expr = agg_expr
        self.names = list(names)

    def _compute_schema(self) -> Schema:
        s = self._children[0].schema()
        fields = [e.to_field(s) for e in self.group_by]
        vdt = self.value_col.to_field(s).dtype
        for n in self.names:
            fields.append(Field(str(n), vdt))
        return Schema(fields)

    def with_children(self, children):
        return Pivot(children[0], self.group_by, self.pivot_col,
                     self.value_col, self.agg_expr, self.names)

    def _params(self):
        return (tuple(e._key() for e in self.group_by), self.pivot_col._key(),
                self.value_col._key(), tuple(self.names))


class Window(LogicalPlan):
    def __init__(self, child, window_exprs: List[Expression],
                 partition_by, order_by, descending, nulls_first, frame=None):
        super().__init__([child])
        self.window_exprs = list(window_exprs)
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        self.descending = list(descending)
        self.nulls_first = list(nulls_first)
        self.frame = frame

    def _compute_schema(self) -> Schema:
        from ..window_exec import window_field
        s = self._children[0].schema()
        fields = list(s.fields)
        for e in self.window_exprs:
            fields.append(window_field(e, s))
        return Schema(fields)

    def with_children(self, children):
        return Window(children[0], self.window_exprs, self.partition_by,
                      self.order_by, self.descending, self.nulls_first,
                      self.frame)

    def _params(self):
        return (tuple(e._key() for e in self.window_exprs),
                tuple(e._key() for e in self.partition_by),
                tuple(e._key() for e in self.order_by),
                tuple(self.descending), tuple(self.nulls_first), repr(self.frame))


class Concat(LogicalPlan):
    def __init__(self, left, right):
        super().__init__([left, right])

    def _compute_schema(self) -> Schema:
        l, r = self._children[0].schema(), self._children[1].schema()
        if l.column_names != r.column_names:
            raise ValueError(
                f"concat requires matching schemas: {l.column_names} vs "
                f"{r.column_names}")
        return l

    def with_children(self, children):
        return Concat(children[0], children[1])

    def clustering_spec(self) -> ClusteringSpec:
        return ClusteringSpec(
            "unknown", self._children[0].num_partitions()
            + self._children[1].num_partitions())


class Join(LogicalPlan):
    def __init__(self, left, right, left_on, right_on, how: str = "inner",
                 strategy: Optional[str] = None, prefix: Optional[str] = None,
                 suffix: Optional[str] = None):
        super().__init__([left, right])
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.how = how
        self.strategy = strategy
        self.prefix = prefix
        self.suffix = suffix

    def _compute_schema(self) -> Schema:
        l, r = self._children[0].schema(), self._children[1].schema()
        if self.how in ("semi", "anti"):
            return l
        fields = list(l.fields)
        lnames = set(l.column_names)
        rkey_names = [e.name() for e in self.right_on]
        lkey_names = [e.name() for e in self.left_on]
        for i, f in enumerate(r.fields):
            if f.name in rkey_names:
                ki = rkey_names.index(f.name)
                if ki < len(lkey_names) and lkey_names[ki] == f.name:
                    continue
            nm = f.name
            if nm in lnames:
                nm = (self.prefix or "right.") + nm + (self.suffix or "")
            fields.append(Field(nm, f.dtype))
        return Schema(fields)

    def with_children(self, children):
        return Join(children[0], children[1], self.left_on, self.right_on,
                    self.how, self.strategy, self.prefix, self.suffix)

    def _params(self):
        return (tuple(e._key() for e in self.left_on),
                tuple(e._key() for e in self.right_on), self.how,
                self.strategy)

    def multiline_display(self):
        return [f"Join[{self.how}] on "
                f"{[repr(e) for e in self.left_on]} = "
                f"{[repr(e) for e in self.right_on]}"]


class Sample(LogicalPlan):
    def __init__(self, child, fraction: Optional[float], size: Optional[int],
                 with_replacement: bool, seed: Optional[int]):
        super().__init__([child])
        self.fraction = fraction
        self.size = size
        self.with_replacement = with_replacement
        self.seed = seed

    def _compute_schema(self) -> Schema:
        return self._children[0].schema()

    def with_children(self, children):
        return Sample(children[0], self.fraction, self.size,
                      self.with_replacement, self.seed)

    def _params(self):
        return (self.fraction, self.size, self.with_replacement, self.seed)


class MonotonicallyIncreasingId(LogicalPlan):
    def __init__(self, child, column_name: str):
        super().__init__([child])
        self.column_name = column_name

    def _compute_schema(self) -> Schema:
        from ..datatype import DataType
        s = self._children[0].schema()
        return Schema([Field(self.column_name, DataType.uint64())] + s.fields)

    def with_children(self, children):
        return MonotonicallyIncreasingId(children[0], self.column_name)

    def _params(self):
        return (self.column_name,)


class Sink(LogicalPlan):
    """Write sink. info = dict(kind=parquet/csv/json/sink, root_dir,
    partition_cols, mode, options, sink)."""

    def __init__(self, child, info: dict):
        super().__init__([child])
        self.info = info

    def _compute_schema(self) -> Schema:
        from ..datatype import DataType
        if self.info.get("kind") == "sink":
            return self.info["sink"].schema()
        fields = [Field("path", DataType.string())]
        for e in self.info.get("partition_cols") or []:
            fields.append(e.to_field(self._children[0].schema()))
        return Schema(fields)

    def with_children(self, children):
        return Sink(children[0], self.info)

    def _params(self):
        return (self.info.get("kind"), self.info.get("root_dir"))
