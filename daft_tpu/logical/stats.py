"""Cardinality / size estimation over logical plans.

The reference propagates ``ApproxStats`` bottom-up (EnrichWithStats,
``src/daft-logical-plan/src/stats.rs``) to drive join reordering and
broadcast decisions. This is the same idea with simpler per-op rules: scan
stats come from parquet metadata via materialized scan tasks (cached on the
Source node); everything else applies selectivity heuristics. Estimates are
deliberately coarse — they only need to rank join orders and pick broadcast
sides, not be exact.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from . import plan as lp

# default selectivities (the reference hardcodes similar factors in its
# ApproxStats arms)
FILTER_SELECTIVITY = 0.2
EQ_FILTER_SELECTIVITY = 0.05
AGG_GROUP_FACTOR = 0.1


@dataclass(frozen=True)
class Stats:
    rows: Optional[float]
    size_bytes: Optional[float]

    def scaled(self, f: float) -> "Stats":
        return Stats(None if self.rows is None else max(self.rows * f, 1.0),
                     None if self.size_bytes is None
                     else max(self.size_bytes * f, 1.0))


UNKNOWN = Stats(None, None)


def _source_stats(node: lp.Source) -> Stats:
    if node.partitions is not None:
        try:
            parts = node.partitions
            # SpillBuffer-backed sources (AQE actuals) track counts at
            # append time — summing would reload spilled entries from disk
            rows = getattr(parts, "total_rows", None)
            size = getattr(parts, "total_bytes", None)
            if rows is None:
                rows = sum(len(p) for p in parts)
            if size is None:
                size = sum(p.size_bytes() or 0 for p in parts)
            return Stats(float(rows), float(size) or None)
        except Exception:
            return UNKNOWN
    tasks = getattr(node, "materialized_tasks", None)
    if tasks is None and node.scan_op is not None:
        try:
            tasks = node.scan_op.to_scan_tasks(node.pushdowns)
            node.materialized_tasks = tasks
        except Exception:
            return UNKNOWN
    if not tasks:
        return Stats(0.0, 0.0)
    rows = 0.0
    size = 0.0
    rows_known = True
    for t in tasks:
        r = t.num_rows()
        if r is None:
            rows_known = False
        else:
            rows += r
        size += t.size_bytes() or 0
    if not rows_known:
        # filters pushed into the scan hide exact counts: estimate from
        # bytes at ~100 B/row, times the filter selectivity
        est = (size / 100.0) * FILTER_SELECTIVITY if size else None
        return Stats(est, size * FILTER_SELECTIVITY if size else None)
    if node.pushdowns.limit is not None:
        rows = min(rows, node.pushdowns.limit)
    return Stats(rows, size or None)


def _filter_selectivity(pred) -> float:
    # an equality against a literal is much more selective than a range
    ops = set()

    def walk(e):
        ops.add(e.op)
        for c in e.args:
            walk(c)

    walk(pred)
    if "eq" in ops and not ({"or"} & ops):
        return EQ_FILTER_SELECTIVITY
    return FILTER_SELECTIVITY


def estimate(node: lp.LogicalPlan) -> Stats:
    """Bottom-up estimated (rows, bytes) for a plan subtree."""
    if isinstance(node, lp.Source):
        return _source_stats(node)
    kids = [estimate(c) for c in node.children]
    if isinstance(node, lp.Filter):
        return kids[0].scaled(_filter_selectivity(node.predicate))
    if isinstance(node, lp.Limit):
        s = kids[0]
        rows = node.limit if s.rows is None else min(s.rows, node.limit)
        return Stats(float(rows), s.size_bytes)
    if isinstance(node, lp.Sample):
        if node.fraction is not None:
            return kids[0].scaled(node.fraction)
        return Stats(float(node.size), None)
    if isinstance(node, lp.Aggregate):
        if not node.group_by:
            return Stats(1.0, 256.0)
        return kids[0].scaled(AGG_GROUP_FACTOR)
    if isinstance(node, lp.Distinct):
        return kids[0].scaled(AGG_GROUP_FACTOR)
    if isinstance(node, lp.Explode):
        return kids[0].scaled(4.0)
    if isinstance(node, lp.Concat):
        l, r = kids
        rows = None if l.rows is None or r.rows is None else l.rows + r.rows
        size = None if l.size_bytes is None or r.size_bytes is None \
            else l.size_bytes + r.size_bytes
        return Stats(rows, size)
    if isinstance(node, lp.Join):
        l, r = kids
        if node.how == "cross":
            if l.rows is None or r.rows is None:
                return UNKNOWN
            return Stats(l.rows * r.rows,
                         None if l.size_bytes is None or r.size_bytes is None
                         else l.size_bytes * max(r.rows, 1.0)
                         + r.size_bytes * max(l.rows, 1.0))
        if node.how in ("semi", "anti"):
            return l.scaled(0.5)
        if node.how == "left":
            return l
        if node.how == "right":
            return r
        # inner equi-join: PK-FK assumption — output ≈ the larger (fact)
        # side (reference stats.rs uses max-side heuristics similarly)
        if l.rows is None or r.rows is None:
            return UNKNOWN
        rows = max(l.rows, r.rows)
        size = None
        if l.size_bytes is not None and r.size_bytes is not None:
            lw = l.size_bytes / max(l.rows, 1.0)
            rw = r.size_bytes / max(r.rows, 1.0)
            size = rows * (lw + rw)
        return Stats(rows, size)
    # row-preserving ops (Project/Sort/Repartition/Window/…)
    if kids:
        return kids[0]
    return UNKNOWN


# ------------------------------------------------------------------ NDV

def column_ndv(node: lp.LogicalPlan, name: str,
               est_rows: Optional[float] = None) -> Optional[float]:
    """Approximate distinct-value count of a column in a plan subtree.

    Integer/date key columns get ``max - min + 1`` from parquet footer
    statistics (exact for the dense surrogate keys join graphs are built
    on: nationkey 0–24 → 25), capped by the subtree's estimated rows —
    a filter that keeps 1 row caps the key's ndv at 1. Columns without
    usable stats fall back to the row estimate (near-unique assumption,
    i.e. FK-join-shaped). The reference reads the same footer stats for
    its scan stats (``daft-scan``'s parquet metadata path).

    ``est_rows``: the caller's row estimate for ``node``, if already
    computed (avoids a redundant estimate() walk)."""
    est = estimate(node).rows if est_rows is None else est_rows
    footer = column_ndv_footer(node, name, est_rows=est)
    return est if footer is None else footer


def column_ndv_footer(node: lp.LogicalPlan, name: str,
                      est_rows: Optional[float] = None) -> Optional[float]:
    """Like :func:`column_ndv` but returns None instead of the near-unique
    row-estimate fallback: only parquet-footer min/max evidence counts.
    For decline-if-huge decisions (the fused-agg cardinality gate) the
    fallback would misfire — a large in-memory groupby on a 5-value key
    has no footer stats and must keep the default path."""
    src = _find_source_with(node, name)
    if src is None:
        return None
    rng = _source_column_range(src, name)
    if rng is None:
        return None
    est = estimate(node).rows if est_rows is None else est_rows
    return rng if est is None else min(rng, est)


def _find_source_with(node: lp.LogicalPlan, name: str):
    if isinstance(node, lp.Source):
        return node if name in node.schema().column_names else None
    for c in node.children:
        try:
            if name in c.schema().column_names:
                return _find_source_with(c, name)
        except Exception:
            return None
    return None


def _source_column_range(node: lp.Source, name: str) -> Optional[float]:
    """(max-min+1) over all files' footer stats for an int/date column."""
    cache = getattr(node, "_ndv_cache", None)
    if cache is None:
        cache = node._ndv_cache = {}
    if name in cache:
        return cache[name]
    out = None
    try:
        tasks = getattr(node, "materialized_tasks", None)
        if tasks is None and node.scan_op is not None:
            tasks = node.scan_op.to_scan_tasks(node.pushdowns)
            node.materialized_tasks = tasks
        lo = hi = None
        seen_paths = set()
        if tasks:
            import pyarrow.parquet as pq
            for t in tasks:
                if t.file_format != "parquet":
                    raise ValueError
                # split tasks share a file; one footer per path, reusing
                # the reader's cached footer when the task carries one
                md_cached = getattr(t, "pq_metadata", None)
                for p in t.paths:
                    if p in seen_paths:
                        continue
                    seen_paths.add(p)
                    md = md_cached if md_cached is not None \
                        and len(t.paths) == 1 else pq.ParquetFile(p).metadata
                    idx = {md.schema.column(i).name: i
                           for i in range(md.num_columns)}.get(name)
                    if idx is None:
                        raise ValueError
                    for rg in range(md.num_row_groups):
                        st = md.row_group(rg).column(idx).statistics
                        if st is None or not st.has_min_max:
                            raise ValueError
                        mn, mx = st.min, st.max
                        if not isinstance(mn, int) or isinstance(mn, bool):
                            import datetime as _dt
                            # date is day-granular so max-min+1 is an ndv
                            # bound; datetime is NOT (a day of distinct
                            # timestamps would collapse to ndv 1) — reject
                            if isinstance(mn, _dt.datetime) \
                                    or not isinstance(mn, _dt.date):
                                raise ValueError
                            mn, mx = mn.toordinal(), mx.toordinal()
                        lo = mn if lo is None else min(lo, mn)
                        hi = mx if hi is None else max(hi, mx)
        if lo is not None:
            out = float(hi - lo + 1)
    except Exception:
        out = None
    cache[name] = out
    return out
