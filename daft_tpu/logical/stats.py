"""Cardinality / size estimation over logical plans.

The reference propagates ``ApproxStats`` bottom-up (EnrichWithStats,
``src/daft-logical-plan/src/stats.rs``) to drive join reordering and
broadcast decisions. This is the same idea with simpler per-op rules: scan
stats come from parquet metadata via materialized scan tasks (cached on the
Source node); everything else applies selectivity heuristics. Estimates are
deliberately coarse — they only need to rank join orders and pick broadcast
sides, not be exact.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from . import plan as lp

# default selectivities (the reference hardcodes similar factors in its
# ApproxStats arms)
FILTER_SELECTIVITY = 0.2
EQ_FILTER_SELECTIVITY = 0.05
AGG_GROUP_FACTOR = 0.1


@dataclass(frozen=True)
class Stats:
    rows: Optional[float]
    size_bytes: Optional[float]

    def scaled(self, f: float) -> "Stats":
        return Stats(None if self.rows is None else max(self.rows * f, 1.0),
                     None if self.size_bytes is None
                     else max(self.size_bytes * f, 1.0))


UNKNOWN = Stats(None, None)


def _source_stats(node: lp.Source) -> Stats:
    if node.partitions is not None:
        try:
            rows = sum(len(p) for p in node.partitions)
            size = sum(p.size_bytes() or 0 for p in node.partitions)
            return Stats(float(rows), float(size) or None)
        except Exception:
            return UNKNOWN
    tasks = getattr(node, "materialized_tasks", None)
    if tasks is None and node.scan_op is not None:
        try:
            tasks = node.scan_op.to_scan_tasks(node.pushdowns)
            node.materialized_tasks = tasks
        except Exception:
            return UNKNOWN
    if not tasks:
        return Stats(0.0, 0.0)
    rows = 0.0
    size = 0.0
    rows_known = True
    for t in tasks:
        r = t.num_rows()
        if r is None:
            rows_known = False
        else:
            rows += r
        size += t.size_bytes() or 0
    if not rows_known:
        # filters pushed into the scan hide exact counts: estimate from
        # bytes at ~100 B/row, times the filter selectivity
        est = (size / 100.0) * FILTER_SELECTIVITY if size else None
        return Stats(est, size * FILTER_SELECTIVITY if size else None)
    if node.pushdowns.limit is not None:
        rows = min(rows, node.pushdowns.limit)
    return Stats(rows, size or None)


def _filter_selectivity(pred) -> float:
    # an equality against a literal is much more selective than a range
    ops = set()

    def walk(e):
        ops.add(e.op)
        for c in e.args:
            walk(c)

    walk(pred)
    if "eq" in ops and not ({"or"} & ops):
        return EQ_FILTER_SELECTIVITY
    return FILTER_SELECTIVITY


def estimate(node: lp.LogicalPlan) -> Stats:
    """Bottom-up estimated (rows, bytes) for a plan subtree."""
    if isinstance(node, lp.Source):
        return _source_stats(node)
    kids = [estimate(c) for c in node.children]
    if isinstance(node, lp.Filter):
        return kids[0].scaled(_filter_selectivity(node.predicate))
    if isinstance(node, lp.Limit):
        s = kids[0]
        rows = node.limit if s.rows is None else min(s.rows, node.limit)
        return Stats(float(rows), s.size_bytes)
    if isinstance(node, lp.Sample):
        if node.fraction is not None:
            return kids[0].scaled(node.fraction)
        return Stats(float(node.size), None)
    if isinstance(node, lp.Aggregate):
        if not node.group_by:
            return Stats(1.0, 256.0)
        return kids[0].scaled(AGG_GROUP_FACTOR)
    if isinstance(node, lp.Distinct):
        return kids[0].scaled(AGG_GROUP_FACTOR)
    if isinstance(node, lp.Explode):
        return kids[0].scaled(4.0)
    if isinstance(node, lp.Concat):
        l, r = kids
        rows = None if l.rows is None or r.rows is None else l.rows + r.rows
        size = None if l.size_bytes is None or r.size_bytes is None \
            else l.size_bytes + r.size_bytes
        return Stats(rows, size)
    if isinstance(node, lp.Join):
        l, r = kids
        if node.how == "cross":
            if l.rows is None or r.rows is None:
                return UNKNOWN
            return Stats(l.rows * r.rows,
                         None if l.size_bytes is None or r.size_bytes is None
                         else l.size_bytes * max(r.rows, 1.0)
                         + r.size_bytes * max(l.rows, 1.0))
        if node.how in ("semi", "anti"):
            return l.scaled(0.5)
        if node.how == "left":
            return l
        if node.how == "right":
            return r
        # inner equi-join: PK-FK assumption — output ≈ the larger (fact)
        # side (reference stats.rs uses max-side heuristics similarly)
        if l.rows is None or r.rows is None:
            return UNKNOWN
        rows = max(l.rows, r.rows)
        size = None
        if l.size_bytes is not None and r.size_bytes is not None:
            lw = l.size_bytes / max(l.rows, 1.0)
            rw = r.size_bytes / max(r.rows, 1.0)
            size = rows * (lw + rw)
        return Stats(rows, size)
    # row-preserving ops (Project/Sort/Repartition/Window/…)
    if kids:
        return kids[0]
    return UNKNOWN
