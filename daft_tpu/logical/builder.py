"""LogicalPlanBuilder: the construction API the DataFrame/SQL layers target.

Reference: ``src/daft-logical-plan/src/builder/mod.rs:59`` and the expression
resolution in ``builder/resolve_expr.rs`` (agg extraction / post-projection).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..expressions import Expression, col, lit
from ..schema import Schema
from . import plan as lp


def _to_exprs(items) -> List[Expression]:
    out = []
    for x in items:
        if isinstance(x, Expression):
            out.append(x)
        elif isinstance(x, str):
            out.append(col(x))
        else:
            raise TypeError(f"expected Expression or column name, got {x!r}")
    return out


class LogicalPlanBuilder:
    def __init__(self, plan: lp.LogicalPlan):
        self._plan = plan

    # ---- sources ---------------------------------------------------------
    @classmethod
    def from_scan(cls, scan_op) -> "LogicalPlanBuilder":
        return cls(lp.Source(scan_op=scan_op, schema=scan_op.schema()))

    @classmethod
    def from_in_memory(cls, partitions, schema: Schema) -> "LogicalPlanBuilder":
        return cls(lp.Source(partitions=list(partitions), schema=schema))

    @property
    def plan(self) -> lp.LogicalPlan:
        return self._plan

    def schema(self) -> Schema:
        return self._plan.schema()

    def _wrap(self, node) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(node)

    # ---- relational ops --------------------------------------------------
    def select(self, exprs: Sequence) -> "LogicalPlanBuilder":
        resolved: List[Expression] = []
        for x in exprs:
            if isinstance(x, str) and x == "*":
                resolved.extend(col(n) for n in self.schema().column_names)
            else:
                resolved.extend(_to_exprs([x]))
        child, resolved = _route_monotonic_id(self._plan, resolved)
        node = _project_maybe_udf(child, resolved)
        return self._wrap(node)

    def with_columns(self, exprs: Sequence[Expression]) -> "LogicalPlanBuilder":
        new_names = {e.name() for e in exprs}
        keep = [col(n) for n in self.schema().column_names
                if n not in new_names]
        child, resolved = _route_monotonic_id(self._plan, keep + list(exprs))
        return self._wrap(_project_maybe_udf(child, resolved))

    def with_columns_renamed(self, mapping: Dict[str, str]) -> "LogicalPlanBuilder":
        exprs = []
        for n in self.schema().column_names:
            exprs.append(col(n).alias(mapping[n]) if n in mapping else col(n))
        return self._wrap(lp.Project(self._plan, exprs))

    def exclude(self, names: Sequence[str]) -> "LogicalPlanBuilder":
        drop = set(names)
        keep = [col(n) for n in self.schema().column_names if n not in drop]
        return self._wrap(lp.Project(self._plan, keep))

    def filter(self, predicate: Expression) -> "LogicalPlanBuilder":
        f = predicate.to_field(self.schema())
        if not f.dtype.is_boolean():
            raise ValueError(f"filter predicate must be Boolean, got {f.dtype!r}")
        return self._wrap(lp.Filter(self._plan, predicate))

    def limit(self, n: int, offset: int = 0) -> "LogicalPlanBuilder":
        return self._wrap(lp.Limit(self._plan, n, offset))

    def explode(self, exprs: Sequence) -> "LogicalPlanBuilder":
        es = [e._unalias() if e.op == "alias" else e for e in _to_exprs(exprs)]
        es = [e if e.op == "explode" else e.explode() for e in es]
        return self._wrap(lp.Explode(self._plan, es))

    def unpivot(self, ids, values, variable_name="variable",
                value_name="value") -> "LogicalPlanBuilder":
        vals = _to_exprs(values) if values else []
        if not vals:
            idn = {e.name() for e in _to_exprs(ids)}
            vals = [col(n) for n in self.schema().column_names if n not in idn]
        return self._wrap(lp.Unpivot(self._plan, _to_exprs(ids), vals,
                                     variable_name, value_name))

    def sort(self, sort_by, descending=False, nulls_first=None
             ) -> "LogicalPlanBuilder":
        keys = _to_exprs(sort_by)
        desc = [descending] * len(keys) if isinstance(descending, bool) \
            else list(descending)
        nf = desc if nulls_first is None else (
            [nulls_first] * len(keys) if isinstance(nulls_first, bool)
            else list(nulls_first))
        return self._wrap(lp.Sort(self._plan, keys, desc, nf))

    def hash_repartition(self, num_partitions: Optional[int],
                         by: Sequence[Expression]) -> "LogicalPlanBuilder":
        n = num_partitions or self._plan.num_partitions()
        return self._wrap(lp.Repartition(
            self._plan, lp.ClusteringSpec("hash", n, tuple(_to_exprs(by)))))

    def random_shuffle(self, num_partitions: Optional[int]) -> "LogicalPlanBuilder":
        n = num_partitions or self._plan.num_partitions()
        return self._wrap(lp.Repartition(
            self._plan, lp.ClusteringSpec("random", n)))

    def into_partitions(self, num_partitions: int) -> "LogicalPlanBuilder":
        return self._wrap(lp.Repartition(
            self._plan, lp.ClusteringSpec("unknown", num_partitions)))

    def distinct(self, on: Optional[Sequence] = None) -> "LogicalPlanBuilder":
        return self._wrap(lp.Distinct(self._plan,
                                      _to_exprs(on) if on else None))

    def sample(self, fraction=None, size=None, with_replacement=False,
               seed=None) -> "LogicalPlanBuilder":
        return self._wrap(lp.Sample(self._plan, fraction, size,
                                    with_replacement, seed))

    def aggregate(self, to_agg: Sequence[Expression],
                  group_by: Sequence[Expression]) -> "LogicalPlanBuilder":
        group_by = _to_exprs(group_by)
        schema = self.schema()
        gb_names = {e.name() for e in group_by}
        for e in to_agg:
            if e.name() in gb_names:
                raise ValueError(
                    f"aggregation output {e.name()!r} collides with a "
                    f"group-by key; alias the aggregation to a new name")
        base_aggs, final_exprs = _extract_aggs(list(to_agg), schema)
        node: lp.LogicalPlan = lp.Aggregate(self._plan, base_aggs, group_by)
        if final_exprs is not None:
            gb_cols = [col(e.name()) for e in group_by]
            node = lp.Project(node, gb_cols + final_exprs)
        return self._wrap(node)

    def pivot(self, group_by, pivot_col, value_col, agg_fn: str,
              names: Optional[List[str]] = None) -> "LogicalPlanBuilder":
        group_by = _to_exprs(group_by)
        pivot_col = _to_exprs([pivot_col])[0]
        value_col = _to_exprs([value_col])[0]
        agg_expr = getattr(value_col, agg_fn)()
        if names is None:
            from ..runners.runner_io import materialize_for_planning
            distinct_b = LogicalPlanBuilder(self._plan).select([pivot_col]) \
                .distinct()
            names = materialize_for_planning(distinct_b)
        # pre-aggregate to one row per (group, pivot) before spreading
        pre = lp.Aggregate(self._plan, [agg_expr], group_by + [pivot_col])
        return self._wrap(lp.Pivot(pre, group_by, pivot_col, value_col,
                                   agg_expr, names))

    def window(self, window_exprs, partition_by, order_by=(), descending=(),
               nulls_first=(), frame=None) -> "LogicalPlanBuilder":
        return self._wrap(lp.Window(self._plan, list(window_exprs),
                                    _to_exprs(partition_by),
                                    _to_exprs(order_by), list(descending),
                                    list(nulls_first), frame))

    def join(self, right: "LogicalPlanBuilder", left_on, right_on,
             how: str = "inner", strategy: Optional[str] = None,
             prefix: Optional[str] = None,
             suffix: Optional[str] = None) -> "LogicalPlanBuilder":
        if how == "cross":
            return self._wrap(lp.Join(self._plan, right._plan, [], [], "cross",
                                      strategy, prefix, suffix))
        return self._wrap(lp.Join(self._plan, right._plan,
                                  _to_exprs(left_on), _to_exprs(right_on),
                                  how, strategy, prefix, suffix))

    def concat(self, other: "LogicalPlanBuilder") -> "LogicalPlanBuilder":
        return self._wrap(lp.Concat(self._plan, other._plan))

    def intersect(self, other: "LogicalPlanBuilder",
                  all: bool = False) -> "LogicalPlanBuilder":
        # desugared to a semi join on all columns (reference lowers similarly)
        cols = [col(n) for n in self.schema().column_names]
        rcols = [col(n) for n in other.schema().column_names]
        base = self if all else self.distinct()
        return base._wrap(lp.Join(base._plan, other._plan, cols, rcols, "semi"))

    def except_(self, other: "LogicalPlanBuilder",
                all: bool = False) -> "LogicalPlanBuilder":
        cols = [col(n) for n in self.schema().column_names]
        rcols = [col(n) for n in other.schema().column_names]
        base = self if all else self.distinct()
        return base._wrap(lp.Join(base._plan, other._plan, cols, rcols, "anti"))

    def union(self, other: "LogicalPlanBuilder",
              all: bool = False) -> "LogicalPlanBuilder":
        out = self.concat(other)
        return out if all else out.distinct()

    def add_monotonically_increasing_id(self, column_name=None
                                        ) -> "LogicalPlanBuilder":
        return self._wrap(lp.MonotonicallyIncreasingId(
            self._plan, column_name or "id"))

    def table_write(self, kind: str, root_dir: str, partition_cols=None,
                    mode: str = "append", options=None) -> "LogicalPlanBuilder":
        return self._wrap(lp.Sink(self._plan, {
            "kind": kind, "root_dir": root_dir,
            "partition_cols": _to_exprs(partition_cols) if partition_cols else None,
            "mode": mode, "options": options or {}}))

    def write_sink(self, sink) -> "LogicalPlanBuilder":
        return self._wrap(lp.Sink(self._plan, {"kind": "sink", "sink": sink}))

    # ---- optimize --------------------------------------------------------
    def optimize(self) -> "LogicalPlanBuilder":
        from .optimizer import Optimizer
        return LogicalPlanBuilder(Optimizer().optimize(self._plan))

    def repr_ascii(self) -> str:
        return self._plan.repr_ascii()


def _route_monotonic_id(child, exprs: List[Expression]):
    """Replace monotonically_increasing_id() expression nodes with a plan-level
    MonotonicallyIncreasingId (reference: DetectMonotonicId rule)."""
    found = False

    def walk(e: Expression) -> Expression:
        nonlocal found
        if e.op == "monotonically_increasing_id":
            found = True
            return col("__mono_id__")
        if not e.args:
            return e
        return e.with_children([walk(c) for c in e.args])

    new = [walk(e) for e in exprs]
    if not found:
        return child, exprs
    return lp.MonotonicallyIncreasingId(child, "__mono_id__"), new


def _project_maybe_udf(child, exprs: List[Expression]):
    """Route projections containing stateful UDFs to UDFProject
    (reference rule: SplitActorPoolProjects)."""
    from ..udf import expr_has_stateful_udf, stateful_udf_concurrency
    if any(expr_has_stateful_udf(e) for e in exprs):
        return lp.UDFProject(child, exprs,
                             stateful_udf_concurrency(exprs))
    return lp.Project(child, exprs)


def _extract_aggs(to_agg: List[Expression], schema: Schema
                  ) -> Tuple[List[Expression], Optional[List[Expression]]]:
    """Split possibly-compound agg expressions into base aggregations plus an
    optional final projection (reference: resolve_expr's agg extraction)."""
    base: List[Expression] = []
    base_keys: Dict[Tuple, str] = {}
    needs_project = False

    def extract(e: Expression) -> Expression:
        nonlocal needs_project
        if e.op.startswith("agg."):
            k = e._key()
            if k not in base_keys:
                nm = e.name() if e.name() not in {b.name() for b in base} \
                    else f"__agg{len(base)}__"
                base_keys[k] = nm
                base.append(e.alias(nm) if nm != e.name() else e)
            return col(base_keys[k])
        if e.op == "col":
            raise ValueError(
                f"column {e.params[0]!r} used in aggregation output without "
                f"an aggregation; wrap it in an agg or add it to group_by")
        needs_project = True
        return e.with_children([extract(c) for c in e.args])

    finals: List[Expression] = []
    direct = True
    for e in to_agg:
        inner = e._unalias()
        if inner.op.startswith("agg."):
            base.append(e)
            finals.append(col(e.name()))
            continue
        direct = False
        finals.append(extract(inner).alias(e.name()))
    if direct and not needs_project:
        return base, None
    return base, finals
