"""Logical-plan fingerprinting for the serving plane's caches.

A fingerprint canonicalizes a logical plan into three parts:

- ``structure`` — a sha256 over the literal-STRIPPED plan tree (every
  ``lit`` expression becomes a ``?`` placeholder) plus the frozen
  ``ExecutionConfig`` — queries differing only in literal values share
  a structure, which is what lets the serving plane count "same shape,
  new parameters" submissions (the jitted-fragment reuse axis);
- ``params`` — the bound-parameter vector: the stripped literal values
  in tree order, canonicalized with ``repr``;
- ``sources`` — one version token per ``Source`` leaf: the scan's file
  set with per-file ``(size, mtime_ns)`` from ``os.stat`` for local
  files, or the object store's version token (size + etag /
  last-modified via ``ObjectSource.version``) for remote ones. Any file
  appearing, disappearing, or changing its version busts both caches; a
  remote object whose store exposes NO version signal keeps the whole
  plan uncacheable (fail-safe — it could change unobservably).

Invalidation rules (documented in the README "Serving plane" section):

- the **plan cache** keys on ``(structure, params, sources)`` — a cached
  physical plan bakes in scan tasks (file lists, row-group pruning), so
  source changes invalidate it as much as literal changes do;
- the **result cache** keys on the same triple — identical query text
  over identical source versions;
- any ``ExecutionConfig`` change busts both (the config repr is hashed
  into ``structure``); process-env ``DAFT_TPU_*`` knob changes do NOT
  (they are read at execution time, not plan time);
- the calibration generation busts both: ``structure`` folds in
  ``device/calibration.plan_token()`` (a quantized digest of every
  actively-overriding learned constant), so plans priced under stale
  constants stop being served once self-tuning flips a decision. The
  separate ``history_structure`` field deliberately EXCLUDES the token —
  admission/latency history keys must stay stable across calibration
  generations and across fleet replicas with different profiles.

Plans are *uncacheable* (→ ``fingerprint()`` returns None, caches
bypassed) when they contain: an in-memory source (caching would pin the
partitions in the cache and ``id()`` keys can be recycled), a write sink
(side effects must re-run), a scan operator that doesn't expose its file
set, or any expression parameter that isn't a plain value (UDF callables
— two different functions can repr at the same recycled address).
"""

from __future__ import annotations

import dataclasses
import datetime
import decimal
import hashlib
import os
from typing import List, Optional, Tuple

from ..expressions.expressions import Expression
from . import plan as lp


@dataclasses.dataclass(frozen=True)
class PlanFingerprint:
    structure: str                 # sha256 hex of the literal-stripped tree
    params: Tuple[str, ...]        # bound literal vector (repr-canonical)
    sources: Tuple[Tuple, ...]     # per-source version tokens
    # ``structure`` WITHOUT the calibration token: admission/latency
    # history keys must survive calibration-generation flips (and match
    # across fleet replicas whose learned profiles differ), unlike
    # cached plans which bake the calibrated decisions in
    history_structure: str = ""

    @property
    def key(self) -> Tuple:
        """Full cache key: shape + literals + source versions."""
        return (self.structure, self.params, self.sources)


class _Uncacheable(Exception):
    """Internal: this plan must bypass the serving caches."""


_SAFE_PARAM_TYPES = (str, int, float, bool, bytes, type(None))


def _canon_value(v, params: List[str]) -> str:
    if isinstance(v, Expression):
        return _canon_expr(v, params)
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_canon_value(x, params) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{k!r}:{_canon_value(v[k], params)}" for k in sorted(v)) + "}"
    if isinstance(v, _SAFE_PARAM_TYPES):
        return repr(v)
    # dtypes and other engine value objects repr stably; anything with a
    # default object repr (memory address) is not a stable identity
    r = repr(v)
    if " at 0x" in r or callable(v):
        raise _Uncacheable(f"unstable plan parameter {type(v).__name__}")
    return r


def _canon_lit(v) -> str:
    """Canonicalize a bound literal VALUE. Stricter than ``_canon_value``:
    a literal keys the result cache, so a merely plausible repr is not
    enough — numpy truncates large-array reprs ('[0, 1, ..., 1999]'), and
    arbitrary objects can repr a recycled address without the literal
    ' at 0x' marker. Only types whose repr is a faithful total encoding
    are allowed; everything else makes the plan uncacheable."""
    if isinstance(v, _SAFE_PARAM_TYPES):
        return repr(v)
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_canon_lit(x) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{k!r}:{_canon_lit(v[k])}" for k in sorted(v)) + "}"
    if isinstance(v, (datetime.date, datetime.time, decimal.Decimal)):
        return repr(v)  # datetime.datetime is a date subclass
    raise _Uncacheable(
        f"literal of type {type(v).__name__} has no stable canonical form")


def _canon_expr(e: Expression, params: List[str]) -> str:
    if e.op == "lit":
        params.append(_canon_lit(e.params[0]))
        return "(lit ?)"
    args = ",".join(_canon_expr(a, params) for a in e.args)
    ps = ",".join(_canon_value(p, params) for p in e.params)
    return f"({e.op} [{args}] [{ps}])"


def _source_version(node: lp.Source) -> Tuple:
    if node.partitions is not None:
        raise _Uncacheable("in-memory source")
    op = node.scan_op
    if op is None:
        raise _Uncacheable("source without scan operator")
    paths = getattr(op, "_paths", None) or getattr(op, "paths", None)
    if not paths:
        raise _Uncacheable(
            f"scan operator {type(op).__name__} exposes no file set")
    versions = []
    for p in paths:
        try:
            st = os.stat(p)
            versions.append((p, int(st.st_size), int(st.st_mtime_ns)))
            continue
        except OSError:
            pass
        # non-statable (remote) object: ask its store for a version
        # token (size + etag / last-modified). A store exposing none
        # leaves the plan uncacheable — a cached plan would keep stale
        # baked row-group ranges and a cached result would serve stale
        # rows if the object changed unobservably.
        ver = None
        if "://" in str(p):
            try:
                from ..io.object_io import get_io_client
                ver = get_io_client().version(str(p))
            except Exception:
                ver = None
        if ver is None:
            raise _Uncacheable(f"source {p!r} has no version signal")
        versions.append((str(p),) + tuple(ver))
    return (type(op).__name__, tuple(versions))


def _canon_node(node: lp.LogicalPlan, params: List[str],
                sources: List[Tuple]) -> str:
    t = type(node).__name__
    if isinstance(node, lp.Sink):
        raise _Uncacheable("write sink (side effects)")
    if isinstance(node, lp.Source):
        sources.append(_source_version(node))
        pd = node.pushdowns
        filt = _canon_value(pd.filters, params) if pd.filters is not None \
            else "-"
        pfilt = _canon_value(pd.partition_filters, params) \
            if pd.partition_filters is not None else "-"
        return (f"(Source #{len(sources) - 1} cols={pd.columns!r} "
                f"filt={filt} pfilt={pfilt} limit={pd.limit!r})")
    fields = []
    for k in sorted(vars(node)):
        if k.startswith("_") or k in ("materialized_tasks",):
            continue
        fields.append(f"{k}={_canon_value(getattr(node, k), params)}")
    kids = ",".join(_canon_node(c, params, sources) for c in node.children)
    return f"({t} {' '.join(fields)} [{kids}])"


def fingerprint(plan: lp.LogicalPlan,
                exec_config=None) -> Optional[PlanFingerprint]:
    """Fingerprint a logical plan, or None when it must bypass caches.
    Never raises — an unexpected node shape degrades to uncached."""
    params: List[str] = []
    sources: List[Tuple] = []
    try:
        tree = _canon_node(plan, params, sources)
    except _Uncacheable:
        return None
    except Exception:
        return None
    cfg = ""
    if exec_config is not None:
        try:
            cfg = repr(dataclasses.asdict(exec_config))
        except Exception:
            cfg = repr(exec_config)
    base = tree + "\x00" + cfg
    history_structure = hashlib.sha256(base.encode()).hexdigest()
    # the calibration-generation token: a plan cached under one set of
    # calibrated constants (combine gating, kernel strategy, fusion
    # pricing all price through calibration.const) must not serve after
    # those constants flip the decision — the token changes, the old
    # entry is simply never hit again and ages out of the LRU
    try:
        from ..device import calibration
        calib = calibration.plan_token()
    except Exception:
        calib = ""
    if calib:
        structure = hashlib.sha256(
            (base + "\x00" + calib).encode()).hexdigest()
    else:
        structure = history_structure
    return PlanFingerprint(structure, tuple(params), tuple(sources),
                           history_structure)
