"""Subquery expressions and their unnest-to-join rewrites.

Reference: ``src/daft-dsl/src/expr/mod.rs:213-292`` models scalar
subqueries, ``InSubquery`` and ``Exists`` as first-class ``Expr`` variants;
``src/daft-logical-plan/src/optimization/rules/unnest_subquery.rs`` rewrites
them into joins. This module is the TPU-native equivalent, designed around
the DataFrame builder instead of a plan-to-plan rule: the SQL planner
parses subqueries into three expression node kinds —

- ``Expression("subquery", (), (info,))``       — scalar subquery
- ``Expression("in_subquery", (lhs,), (info,))`` — ``lhs IN (SELECT …)``
- ``Expression("exists", (), (info,))``          — ``EXISTS (SELECT …)``

— and :func:`apply_where` realizes them while applying a WHERE clause:

- EXISTS / NOT EXISTS      → semi / anti join on the correlation keys
  (uncorrelated: on a constant key against the subquery limited to 1 row)
- IN / NOT IN (SELECT …)   → semi / anti join on (lhs = select item) plus
  correlation keys. NOT IN keeps anti-join semantics: SQL's "any NULL in
  the subquery ⇒ empty result" edge is not modeled (documented caveat,
  same pragmatic rewrite the reference's optimizer performs).
- scalar, uncorrelated     → cross join of the 1-row aggregate
- scalar, correlated       → GROUP BY correlation keys + LEFT JOIN; a
  missing group yields NULL, so comparisons against it are false — SQL's
  empty-subquery-scalar semantics.

Correlation is equality-only (``inner_expr = outer_expr``), the same scope
the reference's rule handles; anything else raises NotImplementedError.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Tuple

from ..expressions.expressions import Expression, col, lit

_uid = itertools.count()


class SubqueryInfo:
    """A parsed subquery, pre-unnesting.

    ``df``            — the inner DataFrame: FROM/joins applied, plain
                        (uncorrelated) WHERE conjuncts applied, and — when
                        ``corr`` is empty — fully projected/aggregated.
    ``corr``          — [(inner_expr, outer_expr)] equality correlation
                        pairs extracted from the inner WHERE.
    ``deferred_aggs`` — when correlated and the select list aggregates,
                        the un-applied select expressions (the rewrite
                        groups them by the correlation keys instead).
    ``value_cols``    — output column names of ``df`` (used when the
                        subquery was fully built by the normal path).
    """

    def __init__(self, df, corr, deferred_aggs, value_cols, resid=None,
                 deferred_group_by=None):
        self.df = df
        self.corr = list(corr)
        self.deferred_aggs = list(deferred_aggs or [])
        self.value_cols = list(value_cols or [])
        # correlated NON-equality conjuncts (outer refs as ``outer_col``
        # markers): realized by the rowid-join rewrite in _semi_anti
        self.resid = list(resid or [])
        # the subquery's OWN GROUP BY keys when its aggregation is
        # deferred: the rewrite groups by correlation keys ∪ these
        self.deferred_group_by = list(deferred_group_by or [])

    def __repr__(self):
        return (f"SubqueryInfo(corr={len(self.corr)}, "
                f"deferred={len(self.deferred_aggs)})")


def scalar_expr(info: SubqueryInfo) -> Expression:
    return Expression("subquery", (), (info,))


def in_expr(lhs: Expression, info: SubqueryInfo) -> Expression:
    return Expression("in_subquery", (lhs,), (info,))


def exists_expr(info: SubqueryInfo) -> Expression:
    return Expression("exists", (), (info,))


# ------------------------------------------------------------------ utils

def split_conjuncts(e: Expression) -> List[Expression]:
    u = e._unalias()
    if u.op == "and":
        return split_conjuncts(u.args[0]) + split_conjuncts(u.args[1])
    return [e]


def and_all(es: List[Expression]) -> Expression:
    out = es[0]
    for e in es[1:]:
        out = out & e
    return out


def free_columns(e: Expression) -> set:
    """Column names referenced by e (not descending into subquery infos)."""
    return set(e.column_names())


def contains_subquery(e: Expression) -> bool:
    if e.op in ("subquery", "in_subquery", "exists"):
        return True
    return any(contains_subquery(a) for a in e.args)


def _replace_node(e: Expression, target: Expression,
                  replacement: Expression) -> Expression:
    if e is target:
        return replacement
    if not e.args:
        return e
    return e.with_children(
        [_replace_node(a, target, replacement) for a in e.args])


# -------------------------------------------------------------- rewrites

def _inner_value_expr(info: SubqueryInfo) -> Tuple[object, Expression]:
    """The subquery's single output as (df, value expression over df)."""
    if info.deferred_aggs:
        if len(info.deferred_aggs) != 1:
            raise NotImplementedError(
                "correlated subquery must select exactly one expression")
        return info.df, info.deferred_aggs[0]
    if len(info.value_cols) != 1:
        raise NotImplementedError(
            f"subquery must select exactly one column, got "
            f"{info.value_cols}")
    return info.df, col(info.value_cols[0])


def _realize_deferred(info: SubqueryInfo):
    """Materialize a correlated AGGREGATING subquery: group the inner frame
    by correlation keys ∪ its own GROUP BY keys, apply the deferred select
    aggregate, and project (correlation keys, value). Returns
    (df, corr_key_names, value_name). The GROUP BY keys fall away after
    grouping — each (corr, group) cell contributes one candidate row."""
    rdf, val = _inner_value_expr(info)
    name = f"__subq{next(_uid)}__"
    key_names, keys = [], []
    for inner, _ in info.corr:
        kn = f"__subqk{next(_uid)}__"
        key_names.append(kn)
        keys.append(inner.alias(kn))
    extra = [g.alias(f"__subqg{next(_uid)}__")
             for g in info.deferred_group_by]
    agg = rdf.groupby(*(keys + extra)).agg(val.alias(name))
    agg = agg.select(*([col(k) for k in key_names] + [col(name)]))
    return agg, key_names, name


def _semi_anti(df, info: SubqueryInfo, anti: bool,
               lhs: Optional[Expression] = None):
    """EXISTS/IN → semi join; NOT variants → anti join."""
    if info.resid:
        return _semi_anti_residual(df, info, anti, lhs)
    how = "anti" if anti else "semi"
    if lhs is not None and info.deferred_aggs:
        # lhs IN (SELECT agg(x) … WHERE corr [GROUP BY g]): aggregate
        # first (per corr ∪ g cell), then semi/anti join on
        # (corr keys, aggregated value)
        rdf, key_names, vn = _realize_deferred(info)
        return df.join(rdf,
                       left_on=[o for _, o in info.corr] + [lhs],
                       right_on=[col(k) for k in key_names] + [col(vn)],
                       how=how)
    left_on = [o for _, o in info.corr]
    right_on = [i for i, _ in info.corr]
    rdf = info.df
    if lhs is not None:
        rdf2, val = _inner_value_expr(info)
        left_on = left_on + [lhs]
        right_on = right_on + [val]
        rdf = rdf2
    if not left_on:
        # uncorrelated EXISTS: does the subquery have any row at all?
        k = f"__exists{next(_uid)}__"
        rdf = rdf.limit(1).select(lit(1).alias(k))
        df2 = df.with_column(k, lit(1))
        out = df2.join(rdf, left_on=[col(k)], right_on=[col(k)], how=how)
        return out.exclude(k) if hasattr(out, "exclude") \
            else out.select(*[col(c) for c in df.column_names])
    return df.join(rdf, left_on=left_on, right_on=right_on, how=how)


def _semi_anti_residual(df, info: SubqueryInfo, anti: bool,
                        lhs: Optional[Expression]):
    """EXISTS/IN with non-equality correlated conjuncts (TPC-DS Q16/Q94's
    ``EXISTS (… WHERE inner.k = outer.k AND inner.wh <> outer.wh)``):

    1. tag the outer frame with a monotonic rowid,
    2. inner-join it to the subquery on the EQUALITY correlation keys
       (inner columns renamed first — self-join-safe),
    3. apply the residual predicates over the joined frame,
    4. semi/anti-join the tagged outer on the surviving rowids.

    The reference's unnest rule stops at equality correlation; this
    rewrite is the standard decorrelation via row identity."""
    if info.deferred_aggs:
        raise NotImplementedError(
            "aggregating subquery with non-equality correlation")
    rid = f"__sqrid{next(_uid)}__"
    tagged = df.add_monotonically_increasing_id(rid)
    rdf = info.df
    # rename every inner column so outer references never collide (the
    # motivating queries self-join the same table)
    ren = {c: f"__sqr{next(_uid)}_{c}__" for c in rdf.column_names}
    rdf = rdf.select(*[col(c).alias(n) for c, n in ren.items()])

    def fix_inner(e: Expression) -> Expression:
        if e.op == "col":
            if e.params[0] not in ren:
                # a silent fall-through here would resolve against the
                # OUTER frame and compare a column to itself
                raise ValueError(
                    f"residual-correlation rewrite: inner column "
                    f"{e.params[0]!r} missing from the subquery's "
                    f"projection {sorted(ren)}")
            return col(ren[e.params[0]])
        if e.op == "outer_col":
            return col(e.params[0])
        if not e.args:
            return e
        return e.with_children([fix_inner(a) for a in e.args])

    left_on = [o for _, o in info.corr]
    right_on = [fix_inner(i) for i, _ in info.corr]
    if lhs is not None:
        rdf2, val = _inner_value_expr(info)
        # re-derive the value expression over the renamed frame
        left_on = left_on + [lhs]
        right_on = right_on + [fix_inner(val)]
    joined = tagged.join(rdf, left_on=left_on, right_on=right_on,
                         how="inner") if left_on else \
        tagged.join(rdf, how="cross")
    joined = joined.where(and_all([fix_inner(r) for r in info.resid]))
    matched = joined.select(col(rid)).distinct()
    how = "anti" if anti else "semi"
    out = tagged.join(matched, left_on=[col(rid)], right_on=[col(rid)],
                      how=how)
    return out.exclude(rid)


def _attach_scalar(df, node: Expression) -> Tuple[object, str]:
    """Join the scalar subquery's value onto df under a unique column name;
    returns (new df, value column name)."""
    info: SubqueryInfo = node.params[0]
    name = f"__subq{next(_uid)}__"
    if info.corr:
        if not info.deferred_aggs:
            raise NotImplementedError(
                "correlated scalar subquery must aggregate (e.g. "
                "SELECT avg(x) …); a bare correlated column select has no "
                "single-value semantics the rewrite can preserve")
        agg, key_names, vn = _realize_deferred(info)
        outers = [outer for _, outer in info.corr]
        if info.deferred_group_by:
            # GROUP BY inside the subquery can yield several rows per
            # correlation key; SQL's scalar context requires exactly one —
            # collapse with a runtime cardinality guard (the grouped LEFT
            # JOIN below would otherwise silently duplicate outer rows,
            # which is what the reference's UnnestScalarSubquery does).
            # SQL evaluates the subquery PER OUTER ROW, so the guard only
            # applies to correlation keys some outer row actually holds —
            # semi-join down to those first.
            ref_keys = df.select(
                *[o.alias(k) for o, k in zip(outers, key_names)]).distinct()
            agg = agg.join(ref_keys,
                           left_on=[col(k) for k in key_names],
                           right_on=[col(k) for k in key_names], how="semi")
            agg = _guard_one_per_key(agg, key_names, vn)
        agg = agg.select(*([col(k) for k in key_names] + [col(vn).alias(name)]))
        out = df.join(agg, left_on=outers,
                      right_on=[col(k) for k in key_names], how="left")
        return out, name
    # uncorrelated: the inner df is fully built and 1-col; SQL requires it
    # to be 1-ROW too. A provably-single-row plan (bare aggregate, LIMIT 1)
    # cross-joins directly; anything else gets a runtime cardinality guard
    # so a multi-row subquery raises instead of silently duplicating every
    # outer row (the reference's UnnestScalarSubquery duplicates silently).
    rdf, val = _inner_value_expr(info)
    rdf = rdf.select(val.alias(name))
    if not _provably_single_row(rdf._builder._plan):
        rdf = _guard_single_row(rdf, name)
    return df.join(rdf, how="cross"), name


def _provably_single_row(plan) -> bool:
    """True when the plan yields EXACTLY one row by construction: a global
    (no-groupby) Aggregate, optionally under projections/sorts (which
    preserve cardinality). LIMIT 1 does NOT qualify — it can yield zero
    rows, and a 0-row cross join would silently drop every outer row where
    SQL wants a NULL scalar (the guard emits that NULL)."""
    from . import plan as lp
    node = plan
    while isinstance(node, (lp.Project, lp.Sort)):
        node = node.children[0]
    return isinstance(node, lp.Aggregate) and not node.group_by


def _guard_one_per_key(agg, key_names: List[str], vn: str):
    """Collapse a (keys…, value) frame to one row per key tuple, raising
    SQL's scalar-cardinality error at execution time when any key holds
    more than one row."""
    from ..datatype import DataType
    from ..udf import udf
    dtype = agg.schema()[vn].dtype
    cnt = f"__subqcnt{next(_uid)}__"
    one = agg.groupby(*[col(k) for k in key_names]).agg(
        col(vn).any_value().alias(vn), col(vn).count("all").alias(cnt))

    @udf(return_dtype=dtype)
    def _check_one(vals, counts):
        if any(c is not None and c > 1 for c in counts.to_pylist()):
            raise ValueError(
                "correlated scalar subquery produced more than one row "
                "for an outer row (its GROUP BY is finer than the "
                "correlation)")
        return vals.to_pylist()

    return one.select(*([col(k) for k in key_names]
                        + [_check_one(col(vn), col(cnt)).alias(vn)]))


def _guard_single_row(rdf, name: str):
    """Collapse to one row carrying (value, row count), then project a
    checked value: count > 1 raises SQL's scalar-cardinality error at
    execution time."""
    from ..datatype import DataType
    from ..udf import udf
    dtype = rdf.schema()[name].dtype
    cnt = f"__subqcnt{next(_uid)}__"
    one = rdf.agg(col(name).agg_list().alias(name),
                  col(name).count("all").alias(cnt))

    @udf(return_dtype=dtype)
    def _check_single(vals, counts):
        # an empty subquery relation can surface its count as NULL through
        # the exchange path (same guard _check_one already carries)
        n = (counts.to_pylist()[0] if len(counts) else 0) or 0
        if n > 1:
            raise ValueError(
                f"scalar subquery produced {n} rows, expected at most 1")
        lst = vals.to_pylist()[0] if len(vals) else []
        return [lst[0] if lst else None]

    return one.select(_check_single(col(name), col(cnt)).alias(name))


def realize_scalars(df, e: Expression) -> Tuple[object, Expression]:
    """Attach every scalar subquery nested in ``e`` onto ``df`` (cross
    join for uncorrelated, grouped left join for correlated) and return
    (new df, e with each subquery node replaced by its attached column).
    The single entry point for scalar realization — WHERE conjuncts, the
    SELECT list, and post-aggregation projections all route here."""
    while True:
        node = _find_scalar(e)
        if node is None:
            return df, e
        df, name = _attach_scalar(df, node)
        e = _replace_node(e, node, col(name))


def _rewrite_conjunct(df, conj: Expression) -> Tuple[Optional[Expression],
                                                     object]:
    """Realize the subquery nodes of one conjunct against df. Returns
    (residual predicate or None, new df)."""
    u = conj._unalias()
    neg = False
    while u.op == "not":
        neg = not neg
        u = u.args[0]._unalias()
    if u.op == "exists":
        return None, _semi_anti(df, u.params[0], anti=neg)
    if u.op == "in_subquery":
        if contains_subquery(u.args[0]):
            raise NotImplementedError("subquery inside IN's left operand")
        return None, _semi_anti(df, u.params[0], anti=neg, lhs=u.args[0])
    # EXISTS/IN nested inside the conjunct (a disjunction like TPC-DS
    # Q10/Q35's ``EXISTS (…) OR EXISTS (…)``) → mark joins
    df, conj = realize_marks(df, conj)
    # scalar subqueries nested anywhere in the conjunct
    df, out = realize_scalars(df, conj)
    return out, df


def _attach_mark(df, node: Expression) -> Tuple[object, Expression]:
    """EXISTS/IN nested in a boolean expression → a mark (boolean) column:
    left-join the outer frame onto the DISTINCT correlation/value keys of
    the subquery tagged TRUE; unmatched rows coalesce to FALSE (exact for
    EXISTS — it never yields NULL). IN-subquery marks route to
    :func:`_attach_in_mark`, which preserves SQL's three-valued logic
    (NULL lhs / NULL-bearing sets yield NULL, visible under negation of
    the enclosing disjunction)."""
    info: SubqueryInfo = node.params[0]
    lhs = node.args[0] if node.op == "in_subquery" else None
    if info.resid:
        raise NotImplementedError(
            "EXISTS with non-equality correlation inside a disjunction")
    if info.deferred_aggs:
        raise NotImplementedError(
            "aggregating subquery inside a disjunction")
    if lhs is not None:
        rdf2, val = _inner_value_expr(info)
        return _attach_in_mark(df, info, lhs, rdf2, val)
    mark = f"__mark{next(_uid)}__"
    left_on = [o for _, o in info.corr]
    right_on = [i for i, _ in info.corr]
    rdf = info.df
    if not left_on:
        # uncorrelated EXISTS in a disjunction: single TRUE/absent flag
        k = f"__markk{next(_uid)}__"
        flag = rdf.limit(1).select(lit(1).alias(k), lit(True).alias(mark))
        out = df.with_column(k, lit(1)).join(
            flag, left_on=[col(k)], right_on=[col(k)], how="left")
        return out.exclude(k), col(mark).fill_null(lit(False))
    knames = []
    keyed_cols = []
    for e in right_on:
        kn = f"__markk{next(_uid)}__"
        knames.append(kn)
        keyed_cols.append(e.alias(kn))
    keyed = rdf.select(*keyed_cols).distinct() \
        .with_column(mark, lit(True))
    out = df.join(keyed, left_on=left_on,
                  right_on=[col(k) for k in knames], how="left")
    return out, col(mark).fill_null(lit(False))


def _attach_in_mark(df, info: SubqueryInfo, lhs: Expression, rdf,
                    val: Expression) -> Tuple[object, Expression]:
    """Null-aware mark for ``lhs IN (SELECT val …)`` nested in a boolean
    expression. SQL three-valued semantics, exactly:

      TRUE  — some element of the (correlation-filtered) set equals lhs
      FALSE — the set is empty, or nothing matches and neither lhs nor
              the set contains NULL
      NULL  — no match, set non-empty, and lhs IS NULL or set has NULL

    Realized as two left joins: one on (corr keys + value) for the match
    mark, one on corr keys alone carrying per-group (row count, has-NULL)
    so unmatched rows can distinguish FALSE from NULL. ``fill_null(False)``
    alone collapses the NULL outcomes to FALSE, which flips rows kept by a
    negated disjunction like ``NOT (p OR x IN (SELECT …))``."""
    mark = f"__mark{next(_uid)}__"
    gnull = f"__markn{next(_uid)}__"
    gcnt = f"__markc{next(_uid)}__"
    vn = f"__markv{next(_uid)}__"
    left_keys = [o for _, o in info.corr]
    inner_keys = [i for i, _ in info.corr]

    def _aliased(exprs):
        names = [f"__markk{next(_uid)}__" for _ in exprs]
        return names, [e.alias(n) for e, n in zip(exprs, names)]

    knames, keyed_cols = _aliased(inner_keys + [val])
    keyed = rdf.select(*keyed_cols).distinct().with_column(mark, lit(True))
    out = df.join(keyed, left_on=left_keys + [lhs],
                  right_on=[col(k) for k in knames], how="left")

    gnames, gcols = _aliased(inner_keys)
    ginfo = rdf.select(*(gcols + [val.alias(vn)]))
    if gnames:
        ginfo = ginfo.groupby(*[col(g) for g in gnames]).agg(
            col(vn).is_null().bool_or().alias(gnull),
            col(vn).count("all").alias(gcnt))
        out = out.join(ginfo, left_on=left_keys,
                       right_on=[col(g) for g in gnames], how="left")
    else:
        ginfo = ginfo.agg(col(vn).is_null().bool_or().alias(gnull),
                          col(vn).count("all").alias(gcnt))
        out = out.join(ginfo, how="cross")

    matched = col(mark).fill_null(lit(False))
    nonempty = col(gcnt).fill_null(lit(0)) > lit(0)
    unknown = lhs.is_null() | col(gnull).fill_null(lit(False))
    flag = matched.if_else(
        lit(True), (nonempty & unknown).if_else(lit(None), lit(False)))
    return out, flag


def _find_setpred(e: Expression) -> Optional[Expression]:
    if e.op in ("in_subquery", "exists"):
        return e
    for a in e.args:
        found = _find_setpred(a)
        if found is not None:
            return found
    return None


def realize_marks(df, e: Expression) -> Tuple[object, Expression]:
    """Replace every EXISTS/IN-subquery node nested in ``e`` with a mark
    column (see ``_attach_mark``); the caller filters on the rewritten
    predicate and the helper columns fall away at the next projection."""
    while True:
        node = _find_setpred(e)
        if node is None:
            return df, e
        df, flag = _attach_mark(df, node)
        e = _replace_node(e, node, flag)


def _find_scalar(e: Expression) -> Optional[Expression]:
    if e.op == "subquery":
        return e
    if e.op in ("in_subquery", "exists"):
        raise NotImplementedError(
            "EXISTS/IN subquery must be a top-level conjunct "
            "(optionally negated), not nested in an expression")
    for a in e.args:
        found = _find_scalar(a)
        if found is not None:
            return found
    return None


def apply_where(df, pred: Expression):
    """df.where(pred), realizing any subquery nodes via joins first. Helper
    columns introduced by scalar-subquery joins stay in the frame; SQL's
    projection step (or the caller) drops them.

    Plain conjuncts apply BEFORE the subquery rewrites: the rewrites wrap
    the frame in joins (and, for residual correlation, a monotonic rowid)
    that block the optimizer's cross-join elimination underneath — the
    equality filters must reach the join graph first."""
    if not contains_subquery(pred):
        return df.where(pred)
    conjs = split_conjuncts(pred)
    plain = [c for c in conjs if not contains_subquery(c)]
    if plain:
        df = df.where(and_all(plain))
    residuals = []
    for conj in conjs:
        if not contains_subquery(conj):
            continue
        residual, df = _rewrite_conjunct(df, conj)
        if residual is not None:
            residuals.append(residual)
    if residuals:
        df = df.where(and_all(residuals))
    return df
