"""Rule-based logical optimizer.

Reference: ``src/daft-logical-plan/src/optimization/optimizer.rs:40-215`` —
rule batches with Once/FixedPoint strategies; rules modeled on the reference's
set (PushDownFilter, PushDownProjection, PushDownLimit, DropRepartition,
SimplifyExpressions, DetectMonotonicId …). Join reordering is planned for a
later round (reference: ``reorder_joins/``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..expressions import Expression, col, lit
from . import plan as lp


class Rule:
    name = "rule"

    def apply(self, plan: lp.LogicalPlan) -> lp.LogicalPlan:
        raise NotImplementedError


class Batch:
    def __init__(self, name: str, rules: List[Rule], strategy: str = "once",
                 max_passes: int = 5):
        self.name = name
        self.rules = rules
        self.strategy = strategy
        self.max_passes = max_passes


class Optimizer:
    def __init__(self, batches: Optional[List[Batch]] = None):
        self.batches = batches or [
            Batch("simplify", [SimplifyExpressions()], "fixed_point"),
            Batch("pushdowns", [EliminateCrossJoin(),
                                SimplifyNullFilteredJoin(),
                                PushDownFilter(),
                                PushDownAntiSemiJoin(),
                                PushDownProjection(), PushDownLimit(),
                                DropRepartition()],
                  "fixed_point"),
            # key-derived filters once pushdowns settle: they ADD filters,
            # so they run in their own once-batches (idempotent by
            # structural dedupe) followed by a pushdown sweep to sink the
            # new predicates into scans
            Batch("derived_filters", [PushDownJoinPredicate(),
                                      FilterNullJoinKey()], "once"),
            # EliminateCrossJoin rides every pushdown sweep: filter motion
            # in these batches can re-form Filter(CrossJoin) patterns long
            # after the first batch settled (3-fact queries like TPC-DS
            # Q25/Q29 surface equi conjuncts above a nested cross here)
            Batch("derived_pushdown", [EliminateCrossJoin(),
                                       PushDownFilter(),
                                       PushDownProjection()],
                  "fixed_point"),
            Batch("joins", [ReorderJoins()], "once"),
            # after the join order settles: key-set transfer into
            # duplicate-collapsing probe sides (its semi joins then get
            # their own pushdown sweep below)
            Batch("semi_reduction", [SemiJoinReduction()], "once"),
            Batch("post_join_pushdowns", [EliminateCrossJoin(),
                                          PushDownFilter(),
                                          PushDownProjection()],
                  "fixed_point"),
            Batch("materialize", [MaterializeScans()], "once"),
        ]

    def optimize(self, plan: lp.LogicalPlan) -> lp.LogicalPlan:
        from ..analysis import plan_sanitizer
        sanitize = plan_sanitizer.is_enabled()
        for batch in self.batches:
            passes = 1 if batch.strategy == "once" else batch.max_passes
            prev_key = None
            for _ in range(passes):
                for rule in batch.rules:
                    if sanitize:
                        before = plan.schema()
                        plan = rule.apply(plan)
                        plan_sanitizer.check_rule(
                            type(rule).__name__, before, plan.schema())
                    else:
                        plan = rule.apply(plan)
                key = plan.semantic_id()
                if key == prev_key:  # fixed point reached (cycle guard)
                    break
                prev_key = key
        return plan


# ---------------------------------------------------------------------------
# expression helpers

def substitute_columns(e: Expression, mapping: Dict[str, Expression]
                       ) -> Expression:
    if e.op == "col" and e.params[0] in mapping:
        sub = mapping[e.params[0]]
        return sub
    if not e.args:
        return e
    return e.with_children([substitute_columns(c, mapping) for c in e.args])


def split_conjuncts(e: Expression) -> List[Expression]:
    if e.op == "and":
        return split_conjuncts(e.args[0]) + split_conjuncts(e.args[1])
    return [e]


def _split_disjuncts(e: Expression) -> List[Expression]:
    if e.op == "or":
        return _split_disjuncts(e.args[0]) + _split_disjuncts(e.args[1])
    return [e]


def combine_conjuncts(es: List[Expression]) -> Expression:
    out = es[0]
    for e in es[1:]:
        out = out & e
    return out


def _has_effectful(e: Expression) -> bool:
    """UDFs and explode change cardinality/cost — don't push filters through."""
    if e.op in ("py_apply", "explode", "udf"):
        return True
    return any(_has_effectful(c) for c in e.args)


# ---------------------------------------------------------------------------
# rules

class SimplifyExpressions(Rule):
    """Basic algebraic simplification (reference: daft-algebra simplify_expr)."""

    name = "simplify_expressions"

    def apply(self, plan):
        def fn(node):
            if isinstance(node, lp.Filter):
                return lp.Filter(node.children[0], simplify(node.predicate))
            if isinstance(node, lp.Project):
                return lp.Project(node.children[0],
                                  [simplify(e) for e in node.exprs])
            return node
        return plan.transform_up(fn)


def simplify(e: Expression) -> Expression:
    if e.args:
        e = e.with_children([simplify(c) for c in e.args])
    # not(not(x)) -> x
    if e.op == "not" and e.args[0].op == "not":
        return e.args[0].args[0]
    # OR-common-conjunct factoring: (A & X) | (A & Y) -> A & (X | Y).
    # TPC-DS Q13/Q48-style predicates repeat the JOIN conditions inside
    # every OR branch; factoring them out lets EliminateCrossJoin find the
    # equi keys instead of evaluating a multi-table cross product.
    if e.op == "or":
        branches = _split_disjuncts(e)
        conj_sets = [split_conjuncts(b) for b in branches]
        common = []
        for c in conj_sets[0]:
            if all(any(c.structurally_eq(x) for x in s)
                   for s in conj_sets[1:]) \
                    and not any(c.structurally_eq(x) for x in common):
                common.append(c)
        if common:
            rests = []
            for s in conj_sets:
                rest = [x for x in s
                        if not any(x.structurally_eq(c) for c in common)]
                rests.append(combine_conjuncts(rest) if rest else lit(True))
            if all(r.op == "lit" and r.params[0] is True for r in rests):
                # every branch was fully absorbed (e.g. A | A): the OR is
                # exactly the common part — recursing would loop forever
                return combine_conjuncts(common)
            out = rests[0]
            for r in rests[1:]:
                out = out | r
            return combine_conjuncts(common + [simplify(out)])
    # x == True -> x ; x == False -> not x
    if e.op in ("eq", "neq"):
        l, r = e.args
        for a, b in ((l, r), (r, l)):
            if b.op == "lit" and isinstance(b.params[0], bool):
                truthy = b.params[0] if e.op == "eq" else not b.params[0]
                return a if truthy else Expression("not", (a,))
    # True & x -> x ; False | x -> x
    if e.op == "and":
        l, r = e.args
        for a, b in ((l, r), (r, l)):
            if a.op == "lit" and a.params[0] is True:
                return b
    if e.op == "or":
        l, r = e.args
        for a, b in ((l, r), (r, l)):
            if a.op == "lit" and a.params[0] is False:
                return b
            if a.op == "lit" and a.params[0] is True:
                return a
    return e


class PushDownFilter(Rule):
    name = "push_down_filter"

    def apply(self, plan):
        def fn(node):
            if not isinstance(node, lp.Filter):
                return node
            child = node.children[0]
            pred = node.predicate
            # merge adjacent filters
            if isinstance(child, lp.Filter):
                return lp.Filter(child.children[0], child.predicate & pred)
            # through project (substituting expressions), if deterministic
            if isinstance(child, lp.Project):
                mapping = {}
                ok = True
                for e in child.exprs:
                    inner = e._unalias()
                    if _has_effectful(inner):
                        if e.name() in pred.column_names():
                            ok = False
                            break
                    mapping[e.name()] = inner
                if ok:
                    new_pred = substitute_columns(pred, mapping)
                    return lp.Project(
                        lp.Filter(child.children[0], new_pred), child.exprs)
            # through ops that don't change rows' values
            if isinstance(child, (lp.Sort, lp.Repartition, lp.Concat)):
                pushed = [lp.Filter(c, pred) for c in child.children]
                return child.with_children(pushed)
            # into join sides
            if isinstance(child, lp.Join) and child.how in ("inner", "left",
                                                            "right", "semi",
                                                            "anti"):
                l_names = set(child.children[0].schema().column_names)
                r_names = set(child.schema().column_names) - l_names
                keep, to_l, to_r = [], [], []
                for c in split_conjuncts(pred):
                    cols_used = set(c.column_names())
                    if cols_used <= l_names and child.how in ("inner", "left",
                                                              "semi", "anti"):
                        to_l.append(c)
                    elif cols_used <= r_names and child.how in ("inner", "right"):
                        # map prefixed names back to right child columns
                        # (exact names first: SQL pre-renames collisions)
                        rc_names = set(child.children[1].schema().column_names)
                        mapping = {}
                        for nm in cols_used:
                            if nm in rc_names:
                                continue  # literal right column, no remap
                            base = nm[6:] if nm.startswith("right.") else nm
                            if base in rc_names:
                                mapping[nm] = col(base)
                        to_r.append(substitute_columns(c, mapping))
                    else:
                        keep.append(c)
                if to_l or to_r:
                    newl = child.children[0]
                    newr = child.children[1]
                    if to_l:
                        newl = lp.Filter(newl, combine_conjuncts(to_l))
                    if to_r:
                        newr = lp.Filter(newr, combine_conjuncts(to_r))
                    new_join = child.with_children([newl, newr])
                    return lp.Filter(new_join, combine_conjuncts(keep)) \
                        if keep else new_join
            # into the scan's pushdowns
            if isinstance(child, lp.Source) and child.scan_op is not None:
                pd = child.pushdowns
                new_f = pred if pd.filters is None else (pd.filters & pred)
                return child.with_pushdowns(pd.with_filters(new_f))
            return node
        return plan.transform_up(fn)


class PushDownProjection(Rule):
    """Column pruning: push required-column sets into scans and collapse
    redundant projections."""

    name = "push_down_projection"

    def apply(self, plan):
        return self._prune(plan, None)

    def _prune(self, node: lp.LogicalPlan,
               required: Optional[Set[str]]) -> lp.LogicalPlan:
        # `required is None` → all columns needed
        if isinstance(node, lp.Source):
            if (required is not None and node.scan_op is not None
                    and node.pushdowns.columns is None):
                avail = node._source_schema.column_names
                filt_cols = set()
                if node.pushdowns.filters is not None:
                    filt_cols = set(node.pushdowns.filters.column_names())
                needed = [c for c in avail if c in (required | filt_cols)]
                if len(needed) < len(avail):
                    return node.with_pushdowns(
                        node.pushdowns.with_columns(needed))
            return node
        if isinstance(node, (lp.Project, lp.UDFProject)):
            child = node.children[0]
            exprs = node.exprs
            if required is not None:
                exprs = [e for e in exprs if e.name() in required] or exprs[:1]
            child_req = set()
            for e in exprs:
                child_req.update(e.column_names())
            # collapse project(project) when outer is pure column selection
            new_child = self._prune(child, child_req)
            if (isinstance(node, lp.Project)
                    and isinstance(new_child, lp.Project)
                    and all(e._unalias().op == "col" for e in exprs)):
                inner_map = {ie.name(): ie for ie in new_child.exprs}
                merged = []
                ok = True
                for e in exprs:
                    src = e._unalias().params[0]
                    if src not in inner_map:
                        ok = False
                        break
                    ie = inner_map[src]
                    merged.append(ie if e.name() == ie.name()
                                  else ie._unalias().alias(e.name()))
                if ok:
                    return lp.Project(new_child.children[0], merged)
            cls = lp.Project if isinstance(node, lp.Project) else lp.UDFProject
            if isinstance(node, lp.UDFProject):
                return lp.UDFProject(new_child, list(exprs), node.concurrency)
            return lp.Project(new_child, list(exprs))
        if isinstance(node, lp.Filter):
            child_req = None if required is None else \
                required | set(node.predicate.column_names())
            return lp.Filter(self._prune(node.children[0], child_req),
                             node.predicate)
        if isinstance(node, lp.Aggregate):
            child_req = set()
            for e in node.aggs + node.group_by:
                child_req.update(e.column_names())
            return lp.Aggregate(self._prune(node.children[0], child_req),
                                node.aggs, node.group_by)
        if isinstance(node, lp.Join):
            l_names = set(node.children[0].schema().column_names)
            r_names = set(node.children[1].schema().column_names)
            if required is None:
                l_req = r_req = None
            else:
                out_l = set()
                out_r = set()
                for nm in required:
                    if nm in l_names:
                        out_l.add(nm)
                    elif nm in r_names:
                        # SQL pre-renames collisions, so the name may be
                        # the right child's literal column
                        out_r.add(nm)
                    else:
                        base = nm[6:] if nm.startswith("right.") else nm
                        out_r.add(base)
                for e in node.left_on:
                    out_l.update(e.column_names())
                for e in node.right_on:
                    out_r.update(e.column_names())
                l_req, r_req = out_l, out_r
            return node.with_children([
                self._prune(node.children[0], l_req),
                self._prune(node.children[1], r_req)])
        if isinstance(node, lp.Sort):
            child_req = None if required is None else \
                required | {c for e in node.sort_by for c in e.column_names()}
            return node.with_children(
                [self._prune(node.children[0], child_req)])
        if isinstance(node, lp.TopN):
            child_req = None if required is None else \
                required | {c for e in node.sort_by for c in e.column_names()}
            return node.with_children(
                [self._prune(node.children[0], child_req)])
        if isinstance(node, lp.Repartition):
            child_req = None if required is None else \
                required | {c for e in node.spec.by for c in e.column_names()}
            return node.with_children(
                [self._prune(node.children[0], child_req)])
        # other nodes: require everything below
        return node.with_children(
            [self._prune(c, None) for c in node.children])


class PushDownLimit(Rule):
    name = "push_down_limit"

    def apply(self, plan):
        def fn(node):
            if not isinstance(node, lp.Limit) or node.offset:
                return node
            child = node.children[0]
            if isinstance(child, lp.Limit):
                return lp.Limit(child.children[0],
                                min(node.limit, child.limit))
            if isinstance(child, (lp.Project,)):
                return child.with_children(
                    [lp.Limit(child.children[0], node.limit)])
            if isinstance(child, lp.Sort):
                return lp.TopN(child.children[0], child.sort_by,
                               child.descending, child.nulls_first, node.limit)
            if isinstance(child, lp.Source) and child.scan_op is not None \
                    and child.pushdowns.filters is None:
                pd = child.pushdowns
                new_l = node.limit if pd.limit is None \
                    else min(pd.limit, node.limit)
                return lp.Limit(child.with_pushdowns(pd.with_limit(new_l)),
                                node.limit)
            return node
        return plan.transform_up(fn)


class DropRepartition(Rule):
    name = "drop_repartition"

    def apply(self, plan):
        def fn(node):
            if isinstance(node, lp.Repartition):
                child = node.children[0]
                # repartition(repartition(x)) -> repartition(x)
                if isinstance(child, lp.Repartition):
                    return lp.Repartition(child.children[0], node.spec)
                # same clustering already → no-op
                cs = child.clustering_spec()
                if (node.spec.kind == "hash" and cs.kind == "hash"
                        and cs.num_partitions == node.spec.num_partitions
                        and [e._key() for e in cs.by]
                        == [e._key() for e in node.spec.by]):
                    return child
            return node
        return plan.transform_up(fn)


class MaterializeScans(Rule):
    """Turn glob-scan sources into concrete scan-task lists
    (reference: MaterializeScans + EnrichWithStats)."""

    name = "materialize_scans"

    def apply(self, plan):
        def fn(node):
            if isinstance(node, lp.Source) and node.scan_op is not None \
                    and getattr(node, "materialized_tasks", None) is None:
                # rules never mutate pushdowns in place (they build new
                # Source nodes), so a cached list — e.g. from the stats
                # pass during join reordering — is still valid here
                node.materialized_tasks = \
                    node.scan_op.to_scan_tasks(node.pushdowns)
            return node
        return plan.transform_up(fn)


class EliminateCrossJoin(Rule):
    """Filter(CrossJoin) with equi-conjuncts spanning both sides → inner
    Join (reference: ``optimization/rules/eliminate_cross_join.rs``). The
    remaining conjuncts stay in a Filter above the new join."""

    name = "eliminate_cross_join"

    def apply(self, plan):
        # one bottom-up pass peels ONE cross layer: converting an upper
        # cross creates the Filter(CrossJoin) pattern below it only after
        # that lower node was already visited. A 17-relation comma join
        # (TPC-DS Q64) needs ~n passes — iterate to a local fixed point
        # rather than relying on the batch's bounded sweep count.
        for _ in range(64):
            new = self._apply_once(plan)
            if new.semantic_id() == plan.semantic_id():
                return new
            plan = new
        return plan

    def _apply_once(self, plan):
        def fn(node):
            if not isinstance(node, lp.Filter):
                return node
            # collapse a stack of Filters (apply_where and the subquery
            # rewrites emit separate .where() calls) so every conjunct is
            # visible to the conversion at once — Q64's 17-relation comma
            # join leaves Filter(Filter(CrossJoin)) otherwise
            preds = [node.predicate]
            child = node.children[0]
            while isinstance(child, lp.Filter):
                preds.append(child.predicate)
                child = child.children[0]
            if not (isinstance(child, lp.Join) and child.how == "cross"):
                return node
            predicate = combine_conjuncts(
                [c for p in preds for c in split_conjuncts(p)])
            lchild, rchild = child.children
            l_names = set(lchild.schema().column_names)
            r_names = set(rchild.schema().column_names)
            left_on, right_on = [], []
            l_only, r_only, rest = [], [], []
            for c in split_conjuncts(predicate):
                if c.op == "eq":
                    a, b = c.args
                    if a.op == "col" and b.op == "col":
                        an, bn = a.params[0], b.params[0]
                        if an in l_names and bn in r_names:
                            left_on.append(a)
                            right_on.append(b)
                            continue
                        if bn in l_names and an in r_names:
                            left_on.append(b)
                            right_on.append(a)
                            continue
                # side-contained conjuncts sink INTO the cross's child —
                # a nested cross (3+-relation comma join, TPC-DS Q18/Q25
                # shape) only converts once its own equis sit directly
                # above it
                refs = set(c.column_names())
                if refs and refs <= l_names:
                    l_only.append(c)
                    continue
                if refs and refs <= r_names:
                    r_only.append(c)
                    continue
                rest.append(c)
            if not left_on and not l_only and not r_only:
                return node
            if l_only:
                lchild = lp.Filter(lchild, combine_conjuncts(l_only))
            if r_only:
                rchild = lp.Filter(rchild, combine_conjuncts(r_only))
            how = "inner" if left_on else "cross"
            join = lp.Join(lchild, rchild, left_on, right_on, how,
                           child.strategy, child.prefix, child.suffix)
            return lp.Filter(join, combine_conjuncts(rest)) if rest else join
        return plan.transform_up(fn)


class ReorderJoins(Rule):
    """Greedy left-deep reordering of inner equi-join trees by estimated
    cardinality (reference: brute-force DP + naive-left-deep in
    ``optimization/rules/reorder_joins/``; here: greedy smallest-first over
    the join graph using ``stats.estimate``, which is O(n²) and picks the
    same orders on TPC-H shapes). Only applies when every key is a plain
    column and relation column names are globally disjoint, so the output
    column SET is order-independent; a final Project restores the original
    column order."""

    name = "reorder_joins"

    def apply(self, plan):
        # top-down, acting only at MAXIMAL inner-join roots: reordering an
        # inner subtree first would wrap it in a Project that blocks
        # flattening at every ancestor join, leaving 4+-relation chains
        # only partially ordered. A Filter directly above the join tree
        # contributes its equality conjuncts as join edges — comma joins
        # (TPC-DS Q64's 17-relation FROM) parse as crosses whose linking
        # equalities live in WHERE, and some links only connect relations
        # that sit far apart in the written order.
        def rec(node, parent_eligible: bool):
            if isinstance(node, lp.Filter) and not parent_eligible \
                    and self._eligible(node.children[0]):
                out = self._try_reorder(node.children[0], node.predicate)
                if out is not None:
                    return out
            elig = self._eligible(node)
            if elig and not parent_eligible:
                out = self._try_reorder(node)
                if out is not None:
                    return out
            return node.with_children(
                [rec(c, elig) for c in node.children])

        return rec(plan, False)

    @staticmethod
    def _eligible(node) -> bool:
        return (isinstance(node, lp.Join)
                and node.how in ("inner", "cross")
                and node.strategy is None
                and all(e.op == "col" for e in node.left_on)
                and all(e.op == "col" for e in node.right_on))

    # -- flatten a maximal inner-equi-join tree ------------------------
    def _flatten(self, node, rels, edges, filters=None):
        if self._eligible(node):
            self._flatten(node.children[0], rels, edges, filters)
            self._flatten(node.children[1], rels, edges, filters)
            for le, re_ in zip(node.left_on, node.right_on):
                edges.append((le.params[0], re_.params[0]))
        elif (filters is not None and isinstance(node, lp.Filter)
              and not _has_effectful(node.predicate)):
            # look through filters interleaved in the join chain: inner
            # joins commute with filters, their cross-relation equalities
            # are join edges in disguise, and PushDownFilter re-sinks the
            # single-relation remainder after the reorder. Effectful
            # (nondeterministic/stateful-UDF) predicates stay opaque —
            # hoisting one above the rebuilt tree would re-evaluate it
            # over the larger joined row set, changing results and
            # invocation counts (same guard as PushDownFilter).
            filters.append(node.predicate)
            self._flatten(node.children[0], rels, edges, filters)
        else:
            rels.append(node)

    def _try_reorder(self, node, filter_pred: Optional[Expression] = None):
        if not self._eligible(node):
            return None
        rels: List[lp.LogicalPlan] = []
        edges: List[tuple] = []
        inner_filters: List[Expression] = []
        self._flatten(node, rels, edges, inner_filters)
        if len(rels) < 3:
            return None
        # column ownership must be unambiguous and globally disjoint
        owner: Dict[str, int] = {}
        for i, r in enumerate(rels):
            for nm in r.schema().column_names:
                if nm in owner:
                    return None
                owner[nm] = i
        for ln, rn in edges:
            if ln not in owner or rn not in owner:
                return None
        # harvest cross-relation equality conjuncts from the Filter above
        # the tree and from filters interleaved inside it; everything else
        # stays as a residual filter on top
        had_cross = self._has_cross(node)
        rest_conjs: List[Expression] = []
        harvested = 0
        preds = ([filter_pred] if filter_pred is not None else []) \
            + inner_filters
        for p in preds:
            for c in split_conjuncts(p):
                u = c._unalias()
                if u.op == "eq":
                    a, b = u.args
                    if a.op == "col" and b.op == "col" \
                            and a.params[0] in owner \
                            and b.params[0] in owner \
                            and owner[a.params[0]] != owner[b.params[0]]:
                        edges.append((a.params[0], b.params[0]))
                        harvested += 1
                        continue
                rest_conjs.append(c)
        from . import stats as lstats
        sizes = []
        for r in rels:
            s = lstats.estimate(r)
            if s.rows is None:
                return None
            sizes.append(max(s.rows, 1.0))
        # greedy by estimated RESULT cardinality: |T ⋈ R| ≈
        # |T|·|R| / max(ndv(keys)) — base-size-only greedy walks straight
        # into m:n low-cardinality joins (TPC-H Q5's s_nationkey =
        # c_nationkey made a 60M-row intermediate of 10k × 150k suppliers
        # × customers through 25 nations). NDVs come from parquet footer
        # min/max (stats.column_ndv); a missing ndv falls back to the
        # relation's rows (near-unique key ⇒ FK-shaped).
        n = len(rels)
        ndv_cache: Dict[tuple, float] = {}

        def ndv(i: int, name: str) -> float:
            key = (i, name)
            if key not in ndv_cache:
                v = lstats.column_ndv(rels[i], name, est_rows=sizes[i])
                ndv_cache[key] = max(v if v is not None else sizes[i], 1.0)
            return ndv_cache[key]

        adj: Dict[int, List[tuple]] = {i: [] for i in range(n)}
        for ln, rn in edges:
            a, b = owner[ln], owner[rn]
            adj[a].append((b, ln, rn))
            adj[b].append((a, rn, ln))
        start = min(range(n), key=lambda i: sizes[i])
        in_set = {start}
        order = [start]
        tree_rows = sizes[start]
        while len(in_set) < n:
            # frontier: candidate → most selective (max-ndv) edge into it
            frontier: Dict[int, float] = {}
            for i in in_set:
                for j, mine, theirs in adj[i]:
                    if j in in_set:
                        continue
                    sel = max(ndv(i, mine), ndv(j, theirs))
                    frontier[j] = max(frontier.get(j, 1.0), sel)
            if not frontier:
                return None  # disconnected graph: leave as written
            best = min(frontier,
                       key=lambda j: tree_rows * sizes[j] / frontier[j])
            in_set.add(best)
            order.append(best)
            tree_rows = max(tree_rows * sizes[best] / frontier[best], 1.0)
        # already in this order with nothing to convert: leave residual
        # filters alone — rebuilding would churn a Project + filter hoist
        # for PushDownFilter to undo
        if order == list(range(n)) and not had_cross and not harvested:
            return None
        # rebuild left-deep (relations may hold nested join trees of their
        # own, e.g. under aggregates — reorder those independently)
        rels = [self.apply(r) for r in rels]
        placed = {order[0]}
        tree = rels[order[0]]
        for idx in order[1:]:
            lkeys, rkeys = [], []
            for j, mine, theirs in adj[idx]:
                if j in placed:
                    lkeys.append(col(theirs))
                    rkeys.append(col(mine))
            placed.add(idx)
            tree = lp.Join(tree, rels[idx], lkeys, rkeys, "inner")
        out_names = node.schema().column_names
        if set(out_names) != set(tree.schema().column_names):
            return None  # safety: must be a pure permutation
        out = lp.Project(tree, [col(nm) for nm in out_names])
        if rest_conjs:
            out = lp.Filter(out, combine_conjuncts(rest_conjs))
        return out

    def _has_cross(self, node) -> bool:
        if isinstance(node, lp.Filter):
            return self._has_cross(node.children[0])
        if not self._eligible(node):
            return False
        return node.how == "cross" \
            or self._has_cross(node.children[0]) \
            or self._has_cross(node.children[1])


def _null_rejecting_cols(conj: Expression) -> set:
    """Columns for which the conjunct cannot hold when they are NULL
    (comparison semantics propagate NULL → filter drops the row). A
    conjunct containing null-tolerant ops (is_null / fill_null /
    coalesce / is_in) contributes nothing."""
    # if_else (CASE) can take a branch that never touches the null column;
    # eq_null_safe is definite on nulls by definition
    tolerant = {"is_null", "fill_null", "coalesce", "is_in", "or", "not",
                "if_else", "eq_null_safe"}

    def has_tolerant(e: Expression) -> bool:
        return e.op in tolerant or any(has_tolerant(c) for c in e.args)

    u = conj._unalias()
    if has_tolerant(u):
        return set()
    if u.op in ("eq", "neq", "lt", "le", "gt", "ge", "between",
                "not_null"):
        return set(u.column_names())
    return set()


class SimplifyNullFilteredJoin(Rule):
    """Filter(outer Join) whose predicate null-rejects a column from the
    null-producing side → strengthen the join (left/right → inner, outer →
    left/right/inner): the filter would drop every unmatched row anyway,
    and inner joins unlock reordering + broadcast (reference:
    ``optimization/rules/simplify_null_filtered_join.rs``)."""

    name = "simplify_null_filtered_join"

    def apply(self, plan):
        def fn(node):
            if not isinstance(node, lp.Filter):
                return node
            child = node.children[0]
            if not (isinstance(child, lp.Join)
                    and child.how in ("left", "right", "outer")):
                return node
            l_names = set(child.children[0].schema().column_names)
            out_names = set(child.schema().column_names)
            r_out = out_names - l_names
            rejected: set = set()
            for c in split_conjuncts(node.predicate):
                rejected |= _null_rejecting_cols(c)
            rejects_left = bool(rejected & l_names)
            rejects_right = bool(rejected & r_out)
            how = child.how
            if how == "left" and rejects_right:
                how = "inner"
            elif how == "right" and rejects_left:
                how = "inner"
            elif how == "outer":
                # rejecting a RIGHT column kills LEFT-unmatched rows
                # (their right columns are NULL) → what remains is a
                # RIGHT join, and vice versa
                if rejects_left and rejects_right:
                    how = "inner"
                elif rejects_right:
                    how = "right"
                elif rejects_left:
                    how = "left"
            if how == child.how:
                return node
            join = lp.Join(child.children[0], child.children[1],
                           child.left_on, child.right_on, how,
                           child.strategy, child.prefix, child.suffix)
            return lp.Filter(join, node.predicate)
        return plan.transform_up(fn)


class PushDownAntiSemiJoin(Rule):
    """Sink semi/anti joins below the left side's Projects and Sorts so
    they filter before wide projections / orderings run (the join output
    schema IS the left schema, so the rewrite is a pure reordering;
    reference: ``optimization/rules/push_down_anti_semi_join.rs``)."""

    name = "push_down_anti_semi_join"

    def apply(self, plan):
        def fn(node):
            if not (isinstance(node, lp.Join)
                    and node.how in ("semi", "anti")):
                return node
            child = node.children[0]
            if isinstance(child, lp.Sort):
                join = lp.Join(child.children[0], node.children[1],
                               node.left_on, node.right_on, node.how,
                               node.strategy)
                return child.with_children([join])
            if isinstance(child, lp.Project):
                # keys must be pure passthroughs of the project's input
                mapping = {}
                for e in child.exprs:
                    inner = e._unalias()
                    if inner.op == "col":
                        mapping[e.name()] = inner
                remapped = []
                for k in node.left_on:
                    ku = k._unalias()
                    if ku.op != "col" or ku.params[0] not in mapping:
                        return node
                    remapped.append(mapping[ku.params[0]])
                join = lp.Join(child.children[0], node.children[1],
                               remapped, node.right_on, node.how,
                               node.strategy)
                return child.with_children([join])
            return node
        return plan.transform_up(fn)


class FilterNullJoinKey(Rule):
    """Null join keys can never match an equi join: pre-filter them on
    the sides whose unmatched rows are NOT preserved (both for inner and
    semi; the probe side of left/right; the right side of anti). Shrinks
    shuffle and build input (reference:
    ``optimization/rules/filter_null_join_key.rs``)."""

    name = "filter_null_join_key"

    def apply(self, plan):
        def not_null_pred(keys):
            preds = [k.not_null() for k in keys
                     if k._unalias().op == "col"]
            return combine_conjuncts(preds) if preds else None

        def already_filtered(child, pred) -> bool:
            return (isinstance(child, lp.Filter)
                    and all(any(c.structurally_eq(ex) for ex in
                                split_conjuncts(child.predicate))
                            for c in split_conjuncts(pred)))

        def fn(node):
            if not isinstance(node, lp.Join) or not node.left_on:
                return node
            filter_left = node.how in ("inner", "semi")
            filter_right = node.how in ("inner", "left", "semi", "anti")
            if node.how == "right":
                filter_left = True
            newl, newr = node.children
            changed = False
            if filter_left:
                p = not_null_pred(node.left_on)
                if p is not None and not already_filtered(newl, p):
                    newl = lp.Filter(newl, p)
                    changed = True
            if filter_right:
                p = not_null_pred(node.right_on)
                if p is not None and not already_filtered(newr, p):
                    newr = lp.Filter(newr, p)
                    changed = True
            if not changed:
                return node
            return node.with_children([newl, newr])
        return plan.transform_up(fn)


class SemiJoinReduction(Rule):
    """Sideways information passing for joins whose probe side collapses
    duplicates: ``Join(A, [Project/Filter]* Distinct/Aggregate(S))`` with
    S estimated much larger than A → pre-filter S with a semi join on
    A's DISTINCT join keys, so the Distinct/Aggregate processes only the
    join-relevant fraction.

    Identity-preserving for inner / semi / anti / left-preserving joins:
    an S row dropped by the key filter can only produce reduced-side rows
    whose join key has no partner in A — rows those join types ignore.
    TPC-H Q21 is the motivating shape: the EXISTS/NOT-EXISTS branches
    each run DISTINCT over the full 6M-row lineitem projection, of which
    ~3% survive the join against the Saudi/failed-order base; with the
    reduction the dedups see only that fraction. The duplicated A
    subtree costs nothing extra at runtime: the executor's subplan
    sharing streams one execution to both consumers.

    Reference analogue: Daft has no sideways information passing; its
    optimizer stops at predicate transfer across keys
    (``optimization/rules/``) — this rule generalizes that to key-SET
    transfer, the classic magic-sets/bloom-reduction rewrite.
    """

    name = "semi_join_reduction"
    MIN_ROWS = 500_000      # don't churn small plans
    RATIO = 4.0             # reduced side must be ≥4x the key side

    def apply(self, plan):
        from . import stats as lstats

        def fn(node):
            if not isinstance(node, lp.Join):
                return node
            # which sides may be reduced without changing semantics:
            # the side whose unmatched rows the join DROPS
            reducible = {"inner": (True, True), "semi": (False, True),
                         "anti": (False, True), "left": (False, True),
                         "right": (True, False)}.get(node.how)
            if reducible is None:
                return node
            newl, newr = node.children
            if reducible[1]:
                newr = self._reduce(newr, node.right_on, newl,
                                    node.left_on, lstats) or newr
            if reducible[0]:
                newl = self._reduce(newl, node.left_on, newr,
                                    node.right_on, lstats) or newl
            if newl is node.children[0] and newr is node.children[1]:
                return node
            return node.with_children([newl, newr])

        return plan.transform_up(fn)

    def _reduce(self, side, side_keys, other, other_keys, lstats):
        """Rewrite ``side`` (the collapsing subtree) or return None."""
        if not all(e.op == "col" for e in side_keys) \
                or not all(e.op == "col" for e in other_keys):
            return None
        # walk down through col-only Projects and Filters to a
        # Distinct / grouped Aggregate, tracking key renames
        chain = []
        keys = [e.params[0] for e in side_keys]
        node = side
        # a UDF in the chain may be stateful/nondeterministic — its
        # values (or a filter's verdicts) over a reduced input could
        # differ
        def has_udf(e):
            return e.op == "udf" or any(has_udf(a) for a in e.args)

        while True:
            if isinstance(node, lp.Filter):
                if has_udf(node.predicate):
                    return None
                chain.append(node)
                node = node.children[0]
                continue
            if isinstance(node, lp.Project):
                if any(has_udf(e) for e in node.exprs):
                    return None
                mapped = []
                byname = {e.name(): e._unalias() for e in node.exprs}
                for k in keys:
                    src = byname.get(k)
                    if src is None or src.op != "col":
                        return None
                    mapped.append(src.params[0])
                keys = mapped
                chain.append(node)
                node = node.children[0]
                continue
            break
        if isinstance(node, lp.Distinct):
            if node.on is not None:
                return None  # keyed dedup: dropped rows are observable
            collapse = node
        elif isinstance(node, lp.Aggregate) and node.group_by:
            # map each join key through the aggregate by OUTPUT name:
            # an aliased group key (GROUP BY b AS a) must filter the
            # SOURCE column b, and every key must resolve unambiguously
            out_to_src = {}
            for g in node.group_by:
                u = g._unalias()
                if u.op == "col":
                    out_to_src.setdefault(g.name(), u.params[0])
            mapped = []
            for k in keys:
                src = out_to_src.get(k)
                if src is None:
                    return None  # not a plain-column group key
                mapped.append(src)
            keys = mapped
            collapse = node
        else:
            return None
        s = collapse.children[0]
        # the Project chain may rename ABOVE the collapse too — map keys
        # through the collapse (Distinct/Agg group keys pass unchanged)
        s_stats = lstats.estimate(s)
        o_stats = lstats.estimate(other)
        if s_stats.rows is None or o_stats.rows is None:
            return None
        if s_stats.rows < self.MIN_ROWS \
                or s_stats.rows < self.RATIO * o_stats.rows:
            return None
        # distinct key projection of the other side, renamed to fresh
        # names (S usually shares column names with A — Q21 self-joins).
        # The tag derives from the CONTENT (key side + key names), not a
        # global counter: identical reducible subtrees must rewrite to
        # identical plans or the executor's semantic-id subplan sharing
        # would run the shared key side once per textual copy
        import hashlib
        tag = hashlib.md5(repr(
            (other.semantic_id(), [e.params[0] for e in other_keys],
             keys)).encode()).hexdigest()[:8]
        knames = [f"__sjr{tag}_{i}__" for i in range(len(other_keys))]
        kproj = lp.Distinct(lp.Project(
            other, [col(e.params[0]).alias(n)
                    for e, n in zip(other_keys, knames)]))
        filtered = lp.Join(s, kproj, [col(k) for k in keys],
                           [col(n) for n in knames], "semi")
        # rebuild the collapse + chain over the filtered source
        out = collapse.with_children([filtered])
        for n in reversed(chain):
            out = n.with_children([out])
        return out


class PushDownJoinPredicate(Rule):
    """Predicate transfer across equi-join keys: a literal comparison
    pinned to one side's key column holds identically for the other
    side's key (rows can only match on equal key values), so clone it
    across — both shuffle inputs shrink (reference:
    ``optimization/rules/push_down_join_predicate.rs``)."""

    name = "push_down_join_predicate"

    _OPS = ("eq", "lt", "le", "gt", "ge", "between", "is_in")

    def apply(self, plan):
        def key_conjuncts(child, key_name):
            """Literal-only conjuncts of an immediate Filter over exactly
            the key column."""
            if not isinstance(child, lp.Filter):
                return []
            out = []
            for c in split_conjuncts(child.predicate):
                u = c._unalias()
                if u.op in self._OPS and set(u.column_names()) == {key_name} \
                        and all(a.op != "col" or a.params[0] == key_name
                                for a in u.args):
                    out.append(c)
            return out

        def fn(node):
            if not (isinstance(node, lp.Join)
                    and node.how in ("inner", "semi")):
                return node
            newl, newr = node.children
            add_l, add_r = [], []
            for lk, rk in zip(node.left_on, node.right_on):
                lu, ru = lk._unalias(), rk._unalias()
                if lu.op != "col" or ru.op != "col":
                    continue
                for c in key_conjuncts(newl, lu.params[0]):
                    t = substitute_columns(c, {lu.params[0]: ru})
                    add_r.append(t)
                for c in key_conjuncts(newr, ru.params[0]):
                    t = substitute_columns(c, {ru.params[0]: lu})
                    add_l.append(t)

            def extend(child, extra):
                if not extra:
                    return child, False
                existing = split_conjuncts(child.predicate) \
                    if isinstance(child, lp.Filter) else []
                fresh = [e for e in extra
                         if not any(e.structurally_eq(x) for x in existing)]
                if not fresh:
                    return child, False
                base = child.children[0] if isinstance(child, lp.Filter) \
                    else child
                return lp.Filter(base, combine_conjuncts(
                    existing + fresh)), True

            newl, cl = extend(newl, add_l)
            newr, cr = extend(newr, add_r)
            if not (cl or cr):
                return node
            return node.with_children([newl, newr])
        return plan.transform_up(fn)
