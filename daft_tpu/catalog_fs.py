"""Filesystem lake catalog: a warehouse directory as a Catalog.

Reference capability: the catalog adapters in
``/root/reference/daft/catalog/`` (pyiceberg/glue/unity wrappers exposing
external tables through one Catalog interface). Here the adapter is
SDK-free over the native lake readers: a warehouse root whose
subdirectories are namespaces and whose table directories are
auto-detected as Iceberg (``metadata/*.metadata.json``), Delta
(``_delta_log/``), Hudi (``.hoodie/``) or plain parquet directories.
Attach it to a Session and the tables are queryable by SQL name::

    sess.attach(FilesystemCatalog("/warehouse", name="lake"))
    sess.sql("SELECT * FROM lake.sales.orders")

Writes go through ``create_table`` (Iceberg format) so round-trips stay
inside the native formats.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from .catalog import Catalog, Identifier, NotFoundError, Table


def _detect_format(path: str) -> Optional[str]:
    if os.path.isdir(os.path.join(path, "metadata")) and any(
            f.endswith(".metadata.json")
            for f in os.listdir(os.path.join(path, "metadata"))):
        return "iceberg"
    if os.path.isdir(os.path.join(path, "_delta_log")):
        return "delta"
    if os.path.isdir(os.path.join(path, ".hoodie")):
        return "hudi"
    if os.path.isdir(path) and any(f.endswith(".parquet")
                                   for f in os.listdir(path)):
        return "parquet"
    return None


class LakeTable(Table):
    """One on-disk lake table; ``read()`` dispatches on detected format."""

    def __init__(self, name: str, path: str, fmt: str):
        self._name = name
        self.path = path
        self.format = fmt

    @property
    def name(self) -> str:
        return self._name

    def schema(self):
        return self.read().schema()

    def read(self, **options: Any):
        import daft_tpu as dt
        if self.format == "iceberg":
            return dt.read_iceberg(self.path, **options)
        if self.format == "delta":
            return dt.read_deltalake(self.path, **options)
        if self.format == "hudi":
            return dt.read_hudi(self.path, **options)
        return dt.read_parquet(os.path.join(self.path, "*.parquet"),
                               **options)

    def write(self, df, mode: str = "append", **options: Any) -> None:
        if mode not in ("append", "overwrite"):
            raise ValueError(f"unsupported write mode {mode!r}")
        if self.format == "iceberg":
            df.write_iceberg(self.path, mode=mode)
        elif self.format == "delta":
            df.write_deltalake(self.path, mode=mode)
        elif self.format == "parquet":
            df.write_parquet(self.path, write_mode=mode)
        else:
            raise NotImplementedError(
                f"writes to {self.format} tables are not supported")


class FilesystemCatalog(Catalog):
    """Warehouse directory → namespaces (subdirectories) → lake tables."""

    def __init__(self, root: str, name: str = "lake"):
        self.root = os.path.abspath(root)
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    # ------------------------------------------------------------ paths
    def _path_of(self, ident: Identifier) -> str:
        return os.path.join(self.root, *ident)

    # ------------------------------------------------------------- SPI
    def _create_namespace(self, ident: Identifier) -> None:
        path = self._path_of(ident)
        if os.path.isdir(path):
            raise ValueError(f"namespace {ident} already exists")
        os.makedirs(path)

    def _create_table(self, ident: Identifier, schema,
                      properties=None) -> Table:
        import pyarrow as pa

        import daft_tpu as dt
        path = self._path_of(ident)
        if _detect_format(path):
            raise ValueError(f"table {ident} already exists")
        empty = pa.table({f.name: pa.array([], type=f.dtype.to_arrow())
                          for f in schema})
        dt.from_arrow(empty).write_iceberg(path)
        return LakeTable(ident[-1], path, "iceberg")

    def _drop_table(self, ident: Identifier) -> None:
        import shutil
        path = self._path_of(ident)
        if _detect_format(path) is None:
            raise NotFoundError(f"table {ident} not found")
        shutil.rmtree(path)

    def _drop_namespace(self, ident: Identifier) -> None:
        path = self._path_of(ident)
        if not os.path.isdir(path):
            raise NotFoundError(f"namespace {ident} not found")
        if os.listdir(path):
            raise ValueError(f"namespace {ident} is not empty")
        os.rmdir(path)

    def _get_table(self, ident: Identifier) -> Table:
        path = self._path_of(ident)
        fmt = _detect_format(path)
        if fmt is None:
            raise NotFoundError(f"table {ident} not found under "
                                f"{self.root}")
        return LakeTable(ident[-1], path, fmt)

    def _has_namespace(self, ident: Identifier) -> bool:
        path = self._path_of(ident)
        return os.path.isdir(path) and _detect_format(path) is None

    def _list_namespaces(self, pattern: Optional[str] = None
                         ) -> List[Identifier]:
        out = []
        for dirpath, dirnames, _ in os.walk(self.root):
            keep = []
            for d in sorted(dirnames):
                full = os.path.join(dirpath, d)
                if d.startswith((".", "_")) or _detect_format(full):
                    continue
                keep.append(d)
                rel = os.path.relpath(full, self.root)
                ident = Identifier(*rel.split(os.sep))
                if pattern is None or str(ident).startswith(pattern):
                    out.append(ident)
            dirnames[:] = keep
        return out

    def _list_tables(self, pattern: Optional[str] = None
                     ) -> List[Identifier]:
        out = []
        for dirpath, dirnames, _ in os.walk(self.root):
            keep = []
            for d in sorted(dirnames):
                full = os.path.join(dirpath, d)
                if d.startswith((".", "_")):
                    continue
                if _detect_format(full):
                    rel = os.path.relpath(full, self.root)
                    ident = Identifier(*rel.split(os.sep))
                    if pattern is None or str(ident).startswith(pattern):
                        out.append(ident)
                else:
                    keep.append(d)
            dirnames[:] = keep
        return out
