"""Series: a named, typed column.

Capability mirror of the reference's ``daft-core`` Series
(``src/daft-core/src/series/mod.rs:32`` — type-erased column with ~60 kernel
modules), re-designed for a two-tier TPU engine:

- **Host tier** (this file): data lives as a pyarrow Array (Arrow C++ memory —
  the survey's build plan §7.1 prescribes Arrow C++ instead of the reference's
  vendored arrow2). Variable-length and nested data is wrangled here; host
  kernels delegate to Arrow C++ compute.
- **Device tier** (``daft_tpu.device``): fixed-width projections of a Series are
  lowered zero-copy(ish) into JAX arrays for the jit-compiled operators.

Python-object columns (``DataType.python()``) are stored as numpy object arrays
(the reference's "pseudo-arrow" ``src/daft-core/src/array/pseudo_arrow``).
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence, Union

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .datatype import DataType
from .schema import Field


def _combine(arr: Union[pa.Array, pa.ChunkedArray]) -> pa.Array:
    if isinstance(arr, pa.ChunkedArray):
        return arr.combine_chunks()
    return arr


class Series:
    """A named, typed, immutable column of values."""

    # __weakref__: the device residency registry (device/pipeline.py)
    # keys decoded-output device planes weakly by the host Series, so a
    # fragment output consumed by another device op skips the re-upload
    __slots__ = ("_name", "_dtype", "_arrow", "_pyobjs", "__weakref__")

    def __init__(self, name: str, dtype: DataType,
                 arrow: Optional[pa.Array] = None,
                 pyobjs: Optional[np.ndarray] = None):
        self._name = name
        self._dtype = dtype
        self._arrow = arrow
        self._pyobjs = pyobjs

    # ---- constructors ----------------------------------------------------
    @classmethod
    def from_arrow(cls, arr: Union[pa.Array, pa.ChunkedArray],
                   name: str = "arrow_series") -> "Series":
        arr = _combine(arr)
        if pa.types.is_dictionary(arr.type):
            arr = arr.dictionary_decode()
        dtype = DataType.from_arrow_type(arr.type)
        # normalize to the canonical arrow repr (e.g. string -> large_string)
        target = dtype.to_arrow()
        if arr.type != target:
            arr = arr.cast(target)
        return cls(name, dtype, arrow=arr)

    @classmethod
    def from_pylist(cls, data: Sequence[Any], name: str = "list_series",
                    dtype: Optional[DataType] = None) -> "Series":
        if dtype is not None and dtype.is_python():
            return cls.from_pyobjects(data, name)
        if dtype is not None and dtype.kind in ("tensor", "image",
                                                "sparse_tensor"):
            # variable-shape multimodal rows (ndarrays) → the struct
            # physical layout (dtype.rs:307-335); a pyobject fallback here
            # would silently disable the whole cast/kernels matrix
            arr = _multimodal_from_rows(data, dtype)
            if arr is not None:
                return cls(name, dtype, arrow=arr)
            return cls.from_pyobjects(data, name)
        try:
            arr = pa.array(data, type=dtype.to_arrow() if dtype is not None else None)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError, pa.ArrowTypeError):
            return cls.from_pyobjects(data, name)
        s = cls.from_arrow(arr, name)
        if dtype is not None and s._dtype != dtype:
            s = cls(name, dtype, arrow=arr.cast(dtype.to_arrow()))
        return s

    @classmethod
    def from_pyobjects(cls, data: Sequence[Any], name: str = "py_series") -> "Series":
        objs = np.empty(len(data), dtype=object)
        for i, v in enumerate(data):
            objs[i] = v
        return cls(name, DataType.python(), pyobjs=objs)

    @classmethod
    def from_numpy(cls, arr: np.ndarray, name: str = "np_series") -> "Series":
        if arr.dtype == object:
            return cls.from_pyobjects(list(arr), name)
        if arr.ndim == 1:
            return cls.from_arrow(pa.array(arr), name)
        # [N, ...] -> fixed-shape tensor column
        inner = DataType.from_numpy_dtype(arr.dtype)
        dt = DataType.tensor(inner, tuple(arr.shape[1:]))
        flat = arr.reshape(arr.shape[0], -1)
        fsl = pa.FixedSizeListArray.from_arrays(pa.array(flat.ravel()), flat.shape[1])
        return cls(name, dt, arrow=fsl)

    @classmethod
    def empty(cls, name: str, dtype: DataType) -> "Series":
        if dtype.is_python():
            return cls(name, dtype, pyobjs=np.empty(0, dtype=object))
        return cls(name, dtype, arrow=pa.array([], type=dtype.to_arrow()))

    @classmethod
    def full_null(cls, name: str, dtype: DataType, length: int) -> "Series":
        if dtype.is_python():
            return cls(name, dtype, pyobjs=np.full(length, None, dtype=object))
        return cls(name, dtype, arrow=pa.nulls(length, type=dtype.to_arrow()))

    # ---- basic props -----------------------------------------------------
    def name(self) -> str:
        return self._name

    def datatype(self) -> DataType:
        return self._dtype

    @property
    def dtype(self) -> DataType:
        return self._dtype

    def field(self) -> Field:
        return Field(self._name, self._dtype)

    def __len__(self) -> int:
        if self._pyobjs is not None:
            return len(self._pyobjs)
        return len(self._arrow)

    def rename(self, name: str) -> "Series":
        return Series(name, self._dtype, self._arrow, self._pyobjs)

    def is_pyobject(self) -> bool:
        return self._pyobjs is not None

    # ---- conversions -----------------------------------------------------
    def to_arrow(self) -> pa.Array:
        if self._pyobjs is not None:
            raise ValueError(f"cannot convert Python-object column {self._name!r} to arrow")
        return self._arrow

    def to_pylist(self) -> List[Any]:
        if self._pyobjs is not None:
            return list(self._pyobjs)
        if self._dtype.kind in ("tensor", "image"):
            return _multimodal_to_rows(self._arrow, self._dtype)
        return self._arrow.to_pylist()

    def to_numpy(self) -> np.ndarray:
        if self._pyobjs is not None:
            return self._pyobjs
        if self._dtype.kind in ("tensor", "image", "sparse_tensor"):
            # variable-shape struct storage: rows are ragged — object array
            out = np.empty(len(self), dtype=object)
            for i, v in enumerate(self.to_pylist()):
                out[i] = v
            return out
        if self._dtype.is_tensor() or self._dtype.is_embedding():
            flat = self._arrow.flatten().to_numpy(zero_copy_only=False)
            n = len(self._arrow)
            return flat.reshape(n, -1)
        return self._arrow.to_numpy(zero_copy_only=False)

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    # ---- selection kernels ----------------------------------------------
    def take(self, indices: Union["Series", np.ndarray, Sequence[int]]) -> "Series":
        if isinstance(indices, Series):
            indices = indices.to_numpy()
        indices = np.asarray(indices)
        if self._pyobjs is not None:
            return Series(self._name, self._dtype, pyobjs=self._pyobjs[indices])
        return Series(self._name, self._dtype,
                      arrow=self._arrow.take(pa.array(indices)))

    def filter(self, mask: Union["Series", np.ndarray]) -> "Series":
        if isinstance(mask, Series):
            m = mask.to_arrow()
        else:
            m = pa.array(np.asarray(mask, dtype=np.bool_))
        if self._pyobjs is not None:
            keep = np.asarray(m.to_numpy(zero_copy_only=False), dtype=np.bool_)
            keep = np.where(np.isnan(keep.astype(float)), False, keep) \
                if keep.dtype != np.bool_ else keep
            return Series(self._name, self._dtype, pyobjs=self._pyobjs[keep])
        return Series(self._name, self._dtype,
                      arrow=self._arrow.filter(m, null_selection_behavior="drop"))

    def slice(self, start: int, end: int) -> "Series":
        n = len(self)
        start = max(0, min(start, n))
        end = max(start, min(end, n))
        if self._pyobjs is not None:
            return Series(self._name, self._dtype, pyobjs=self._pyobjs[start:end])
        return Series(self._name, self._dtype, arrow=self._arrow.slice(start, end - start))

    def head(self, n: int) -> "Series":
        return self.slice(0, n)

    def broadcast(self, length: int) -> "Series":
        if len(self) == length:
            return self
        if len(self) != 1:
            raise ValueError(f"cannot broadcast series of length {len(self)} to {length}")
        if self._pyobjs is not None:
            out = np.empty(length, dtype=object)
            for i in range(length):
                out[i] = self._pyobjs[0]
            return Series(self._name, self._dtype, pyobjs=out)
        return self.take(np.zeros(length, dtype=np.int64))

    @classmethod
    def concat(cls, series_list: List["Series"]) -> "Series":
        assert series_list, "concat of empty list"
        first = series_list[0]
        if any(s.is_pyobject() for s in series_list):
            objs = np.concatenate([
                s._pyobjs if s.is_pyobject() else np.array(s.to_pylist(), dtype=object)
                for s in series_list])
            return cls(first._name, DataType.python(), pyobjs=objs)
        arrays = [s.to_arrow() for s in series_list]
        # a NULL-typed piece (all-null batch) must never drive the target
        # type — casting null→anything is free, anything→null impossible
        tgt = first
        if first._dtype.is_null():
            tgt = next((s for s in series_list if not s._dtype.is_null()),
                       first)
        t = tgt._dtype.to_arrow()
        arrays = [a if a.type == t else a.cast(t) for a in arrays]
        return cls(first._name, tgt._dtype,
                   arrow=_combine(pa.chunked_array(arrays)))

    # ---- null handling ---------------------------------------------------
    def is_null(self) -> "Series":
        if self._pyobjs is not None:
            vals = np.array([v is None for v in self._pyobjs])
            return Series(self._name, DataType.bool(), arrow=pa.array(vals))
        return Series(self._name, DataType.bool(), arrow=pc.is_null(self._arrow))

    def not_null(self) -> "Series":
        if self._pyobjs is not None:
            vals = np.array([v is not None for v in self._pyobjs])
            return Series(self._name, DataType.bool(), arrow=pa.array(vals))
        return Series(self._name, DataType.bool(), arrow=pc.is_valid(self._arrow))

    def fill_null(self, fill: "Series") -> "Series":
        fv = fill.to_arrow()[0] if isinstance(fill, Series) else pa.scalar(fill)
        return Series(self._name, self._dtype, arrow=pc.fill_null(self._arrow, fv))

    def null_count(self) -> int:
        if self._pyobjs is not None:
            return sum(1 for v in self._pyobjs if v is None)
        return self._arrow.null_count

    # ---- casting ---------------------------------------------------------
    def cast(self, dtype: DataType) -> "Series":
        if dtype == self._dtype:
            return self
        if dtype.is_python():
            return Series.from_pyobjects(self.to_pylist(), self._name)
        if self._pyobjs is not None:
            return Series.from_pylist(list(self._pyobjs), self._name, dtype=dtype)
        if dtype.is_null():
            # any → null: only null values can occupy a null column
            # (pyarrow has no cast kernel for this direction)
            return Series(self._name, dtype,
                          arrow=pa.nulls(len(self._arrow)))
        mm = _multimodal_cast(self, dtype)
        if mm is not None:
            return mm
        target = dtype.to_arrow()
        try:
            out = self._arrow.cast(target)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
            out = self._arrow.cast(target, safe=False)
        return Series(self._name, dtype, arrow=out)

    # ---- hashing (partitioning / joins on host) -------------------------
    def hash(self, seed: Optional["Series"] = None) -> "Series":
        """64-bit hash per row (invalid rows hash to the hash of the seed).

        Reference capability: ``src/daft-core/src/array/ops/hash.rs``. Here:
        splitmix64 over fixed-width reinterpretation; strings/binary hash via
        byte-level FNV-1a vectorized in numpy.
        """
        h = _hash_array(self)
        if seed is not None:
            sv = seed.to_numpy().astype(np.uint64)
            h = _splitmix64(h ^ sv)
        return Series(self._name, DataType.uint64(), arrow=pa.array(h))

    def minhash(self, num_hashes: int, ngram_size: int = 1,
                seed: int = 1) -> "Series":
        """MinHash signature per string row → fixed_size_list<uint32>[num_hashes].

        Reference capability: ``src/daft-minhash/src/lib.rs`` (word shingles,
        k universal-hash permutations, per-permutation minimum). Native C++
        path in ``daft_tpu/native``; Python fallback keeps the same contract.
        """
        if not self._dtype.is_string():
            raise ValueError(f"minhash expects a string column, got {self._dtype!r}")
        from . import native
        arr = self.to_arrow().cast(pa.large_binary())
        bufs = arr.buffers()
        offsets = np.frombuffer(bufs[1], dtype=np.int64, count=len(arr) + 1,
                                offset=arr.offset * 8)
        data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None \
            else np.empty(0, dtype=np.uint8)
        valid = np.asarray(pc.is_valid(arr).to_numpy(zero_copy_only=False),
                           dtype=np.bool_)
        if native.AVAILABLE:
            sig = native.minhash(offsets, data, valid, num_hashes,
                                 ngram_size, seed)
        else:
            sig = _minhash_fallback(self.to_pylist(), num_hashes,
                                    ngram_size, seed)
        flat = pa.array(sig.ravel(), type=pa.uint32())
        out = pa.FixedSizeListArray.from_arrays(flat, num_hashes)
        if not valid.all():
            mask = pa.array(~valid)
            out = pc.if_else(mask, pa.nulls(len(self), out.type), out)
        return Series(self._name, DataType.fixed_size_list(
            DataType.uint32(), num_hashes), arrow=out)

    # ---- repr ------------------------------------------------------------
    def __repr__(self):
        preview = self.to_pylist()[:10]
        return f"Series[{self._name}: {self._dtype!r}] {preview}"

    def __iter__(self):
        return iter(self.to_pylist())


def _fsl_values_offsets(arr: pa.Array):
    """FixedSizeList array → (flat values, per-row width, validity).
    ``flatten()`` (not ``.values``) — it respects the array's slice
    offset; ``.values`` spans the whole backing buffer."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    width = arr.type.list_size
    valid = np.asarray(pc.is_valid(arr).to_numpy(zero_copy_only=False),
                       dtype=np.bool_)
    return arr.flatten(), width, valid


def _list_window(data: pa.Array):
    """List array → (values restricted to this array's window, offsets
    REBASED to that window). ``offsets`` honors the slice but stays
    absolute into the backing buffer; ``values`` ignores the slice —
    this pairs them correctly for sliced arrays."""
    offs = np.asarray(data.offsets.to_numpy(zero_copy_only=False),
                      dtype=np.int64)
    window = data.values.slice(int(offs[0]), int(offs[-1] - offs[0]))
    return window, offs - offs[0]


def _wrap_list(flat: pa.Array, counts: np.ndarray,
               valid: np.ndarray) -> pa.Array:
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    off = pa.array(offsets, pa.int64())
    out = pa.LargeListArray.from_arrays(off, flat)
    if not valid.all():
        out = pc.if_else(pa.array(valid), out, pa.nulls(len(valid), out.type))
    return out


def _multimodal_from_rows(data: Sequence[Any],
                          dtype: DataType) -> Optional[pa.Array]:
    """ndarray rows → the struct physical for variable-shape tensor /
    image / sparse-tensor columns. None when a row isn't array-like."""
    from .datatype import ImageMode
    rows = []
    for v in data:
        if v is None:
            rows.append(None)
            continue
        if isinstance(v, dict):
            if dtype.kind != "sparse_tensor":
                return None  # dict rows only mean something for sparse
            rows.append(v)
            continue
        try:
            rows.append(np.asarray(v))
        except Exception:
            return None
        if rows[-1].dtype == object:
            return None
    n = len(rows)
    valid = np.array([r is not None for r in rows], dtype=np.bool_)
    mask = pa.array(~valid) if not valid.all() else None
    try:
        return _multimodal_build(rows, dtype, n, valid, mask)
    except Exception:
        return None  # non-conforming rows → pyobject fallback


def _multimodal_build(rows, dtype, n, valid, mask):
    from .datatype import ImageMode
    if dtype.kind == "tensor":
        inner = dtype._params[0].to_physical().to_arrow()
        flats, shapes, counts, scounts = [], [], [], []
        for r in rows:
            if r is None:
                counts.append(0)
                scounts.append(0)
            else:
                flats.append(r.ravel())
                shapes.append(np.asarray(r.shape, np.uint64))
                counts.append(r.size)
                scounts.append(r.ndim)
        flat = np.concatenate(flats) if flats else np.empty(0)
        shp = np.concatenate(shapes) if shapes else np.empty(0, np.uint64)
        data_col = _wrap_list(pa.array(flat).cast(inner),
                              np.asarray(counts, np.int64), valid)
        shape_col = _wrap_list(pa.array(shp, pa.uint64()),
                               np.asarray(scounts, np.int64), valid)
        return pa.StructArray.from_arrays([data_col, shape_col],
                                          ["data", "shape"], mask=mask)
    if dtype.kind == "image":
        mode = dtype._params[0]
        flats, counts, chans, hs, ws, modes = [], [], [], [], [], []
        for r in rows:
            if r is None:
                counts.append(0)
                chans.append(0); hs.append(0); ws.append(0); modes.append(0)
                continue
            if r.ndim == 2:
                r = r[:, :, None]
            h, w, c = r.shape
            m = mode if mode is not None else \
                {1: ImageMode.L, 2: ImageMode.LA, 3: ImageMode.RGB,
                 4: ImageMode.RGBA}.get(c)
            flats.append(r.ravel())
            counts.append(r.size)
            chans.append(c); hs.append(h); ws.append(w)
            modes.append(m.value if m is not None else 0)
        if mode is not None:
            inner = DataType.from_numpy_dtype(mode.np_dtype).to_arrow()
        else:
            dts = {f.dtype for f in flats}
            if len(dts) > 1 or (dts and next(iter(dts)) not in (
                    np.dtype(np.uint8),)):
                raise ValueError("mode-less image rows must be uint8")
            inner = DataType.uint8().to_arrow()
        flat = np.concatenate(flats) if flats else np.empty(0)
        data_col = _wrap_list(pa.array(flat).cast(inner),
                              np.asarray(counts, np.int64), valid)
        return pa.StructArray.from_arrays(
            [data_col, pa.array(chans, pa.uint16()),
             pa.array(hs, pa.uint32()), pa.array(ws, pa.uint32()),
             pa.array(modes, pa.uint8())],
            ["data", "channel", "height", "width", "mode"], mask=mask)
    if dtype.kind == "sparse_tensor":
        inner = dtype._params[0].to_physical().to_arrow()
        vals, idxs, shps = [], [], []
        vcounts, icounts, scounts = [], [], []
        for r in rows:
            if r is None:
                vcounts.append(0); icounts.append(0); scounts.append(0)
                continue
            if isinstance(r, dict):
                v = np.asarray(r["values"]); i = np.asarray(r["indices"],
                                                            np.uint64)
                shp = np.asarray(r["shape"], np.uint64)
            else:
                flat = r.ravel()
                nz = np.flatnonzero(flat)
                v = flat[nz]; i = nz.astype(np.uint64)
                shp = np.asarray(r.shape, np.uint64)
            vals.append(v); idxs.append(i); shps.append(shp)
            vcounts.append(len(v)); icounts.append(len(i))
            scounts.append(len(shp))
        def cat(parts, dt=None):
            return np.concatenate(parts) if parts else np.empty(0, dt or np.float64)
        values_col = _wrap_list(pa.array(cat(vals)).cast(inner),
                                np.asarray(vcounts, np.int64), valid)
        idx_col = _wrap_list(pa.array(cat(idxs, np.uint64), pa.uint64()),
                             np.asarray(icounts, np.int64), valid)
        shp_col = _wrap_list(pa.array(cat(shps, np.uint64), pa.uint64()),
                             np.asarray(scounts, np.int64), valid)
        return pa.StructArray.from_arrays([values_col, idx_col, shp_col],
                                          ["values", "indices", "shape"],
                                          mask=mask)
    return None


def _multimodal_to_rows(arr: pa.Array, dtype: DataType) -> List[Any]:
    """Struct-physical tensor/image columns → ndarray rows (what users
    put in is what they get back)."""
    arr = _combine(arr)
    out: List[Any] = []
    valid = np.asarray(pc.is_valid(arr).to_numpy(zero_copy_only=False),
                       dtype=np.bool_)
    if dtype.kind == "tensor":
        data = arr.field("data")
        shape = arr.field("shape")
        for i in range(len(arr)):
            if not valid[i]:
                out.append(None)
                continue
            d = np.asarray(data[i].as_py())
            s = tuple(int(x) for x in (shape[i].as_py() or ()))
            out.append(d.reshape(s) if s else d)
        return out
    if dtype.kind == "image":
        data = arr.field("data")
        hs = arr.field("height")
        ws = arr.field("width")
        cs = arr.field("channel")
        for i in range(len(arr)):
            if not valid[i]:
                out.append(None)
                continue
            d = np.asarray(data[i].as_py())
            c = int(cs[i].as_py())
            shape = (int(hs[i].as_py()), int(ws[i].as_py())) \
                if c == 1 else (int(hs[i].as_py()), int(ws[i].as_py()), c)
            out.append(d.reshape(shape))  # L-mode rows stay 2-D, like PIL
        return out
    return arr.to_pylist()


def _multimodal_cast(s: "Series", dtype: DataType) -> "Optional[Series]":
    """Cast directions pyarrow has no kernels for: the multimodal matrix
    between fixed-shape and variable-shape tensor/image types and the
    dense↔sparse tensor pair (reference:
    ``src/daft-core/src/array/ops/cast.rs`` — the physical layouts here
    mirror ``dtype.rs:307-335``)."""
    src = s.datatype()
    arr = s.to_arrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)

    def done(struct):
        return Series(s.name(), dtype, arrow=struct)

    # fixed-shape tensor/embedding → variable Tensor --------------------
    if src.kind in ("fixed_shape_tensor", "embedding") \
            and dtype.kind == "tensor":
        inner, shape = (src._params if src.kind == "fixed_shape_tensor"
                        else (src._params[0], (src._params[1],)))
        flat, width, valid = _fsl_values_offsets(arr)
        tgt_inner = dtype._params[0].to_physical().to_arrow()
        if flat.type != pa.large_list(tgt_inner).value_type:
            flat = flat.cast(tgt_inner)
        # flatten() drops null rows' slots, so null rows count 0
        counts = np.where(valid, width, 0).astype(np.int64)
        data = _wrap_list(flat, counts, valid)
        shape_flat = pa.array(np.tile(np.asarray(shape, np.uint64),
                                      int(valid.sum())))
        shapes = _wrap_list(shape_flat,
                            np.where(valid, len(shape), 0).astype(np.int64),
                            valid)
        return done(pa.StructArray.from_arrays(
            [data, shapes], ["data", "shape"],
            mask=pa.array(~valid) if not valid.all() else None))

    # FixedShapeImage → Image -------------------------------------------
    if src.kind == "fixed_shape_image" and dtype.kind == "image":
        mode, h, w = src._params
        flat, width, valid = _fsl_values_offsets(arr)
        data = _wrap_list(flat, np.where(valid, width, 0).astype(np.int64),
                          valid)
        mk = lambda v, t: pa.array(np.full(n, v), t)  # noqa: E731
        return done(pa.StructArray.from_arrays(
            [data, mk(mode.num_channels, pa.uint16()),
             mk(h, pa.uint32()), mk(w, pa.uint32()),
             mk(mode.value, pa.uint8())],
            ["data", "channel", "height", "width", "mode"],
            mask=pa.array(~valid) if not valid.all() else None))

    # Image → Tensor (shape = [h, w, c] per row) ------------------------
    if src.kind == "image" and dtype.kind == "tensor":
        valid = np.asarray(pc.is_valid(arr).to_numpy(zero_copy_only=False),
                           dtype=np.bool_)
        data = arr.field("data")
        h = arr.field("height").to_numpy(zero_copy_only=False)
        w = arr.field("width").to_numpy(zero_copy_only=False)
        c = arr.field("channel").to_numpy(zero_copy_only=False)
        hwc = np.stack([np.where(valid, h, 0), np.where(valid, w, 0),
                        np.where(valid, c, 0)], axis=1).astype(np.uint64)
        shapes = _wrap_list(pa.array(hwc.ravel()),
                            np.full(n, 3, np.int64), valid)
        tgt_inner = dtype._params[0].to_physical().to_arrow()
        if data.type.value_type != tgt_inner:
            data = data.cast(pa.large_list(tgt_inner))
        elif not isinstance(data.type, pa.LargeListType):
            data = data.cast(pa.large_list(data.type.value_type))
        return done(pa.StructArray.from_arrays(
            [data, shapes], ["data", "shape"],
            mask=pa.array(~valid) if not valid.all() else None))

    # Tensor → SparseTensor (drop zeros, record indices) ----------------
    if src.kind == "tensor" and dtype.kind == "sparse_tensor":
        valid = np.asarray(pc.is_valid(arr).to_numpy(zero_copy_only=False),
                           dtype=np.bool_)
        data = _combine(arr.field("data"))
        shape_col = arr.field("shape")
        flat, offs = _list_window(data)  # slice-safe: rebased offsets
        flat = np.asarray(flat.to_numpy(zero_copy_only=False))
        spans = np.diff(offs)
        nz = (flat != 0) & np.repeat(valid, spans)
        row_of = np.repeat(np.arange(n), spans)
        counts = np.bincount(row_of[nz], minlength=n).astype(np.int64) \
            if len(flat) else np.zeros(n, np.int64)
        row_base = np.repeat(offs[:-1], spans)
        idx_all = (np.arange(len(flat)) - row_base).astype(np.uint64)
        tgt_inner = dtype._params[0].to_physical().to_arrow()
        values = _wrap_list(pa.array(flat[nz]).cast(tgt_inner),
                            counts, valid)
        indices = _wrap_list(pa.array(idx_all[nz]), counts, valid)
        return done(pa.StructArray.from_arrays(
            [values, indices, shape_col.cast(pa.large_list(pa.uint64()))],
            ["values", "indices", "shape"],
            mask=pa.array(~valid) if not valid.all() else None))

    # SparseTensor → Tensor (dense reconstruction) ----------------------
    if src.kind == "sparse_tensor" and dtype.kind == "tensor":
        valid = np.asarray(pc.is_valid(arr).to_numpy(zero_copy_only=False),
                           dtype=np.bool_)
        values = _combine(arr.field("values"))
        indices = _combine(arr.field("indices"))
        shape_col = _combine(arr.field("shape"))
        shp_flat_a, shp_offs = _list_window(shape_col)
        shp_flat = np.asarray(shp_flat_a.to_numpy(zero_copy_only=False),
                              dtype=np.int64)
        dense_counts = np.ones(n, np.int64)
        for i in range(n):
            dims = shp_flat[shp_offs[i]:shp_offs[i + 1]]
            dense_counts[i] = int(np.prod(dims)) if len(dims) else 0
        dense_counts = np.where(valid, dense_counts, 0)
        total = int(dense_counts.sum())
        tgt_inner = dtype._params[0].to_physical()
        out_flat = np.zeros(total, dtype=tgt_inner.device_repr())
        bases = np.concatenate([[0], np.cumsum(dense_counts)])[:-1]
        v_flat_a, v_offs = _list_window(values)
        i_flat_a, _ = _list_window(indices)
        v_flat = np.asarray(v_flat_a.to_numpy(zero_copy_only=False))
        i_flat = np.asarray(i_flat_a.to_numpy(zero_copy_only=False),
                            dtype=np.int64)
        spans = np.diff(v_offs)
        keep = np.repeat(valid, spans)
        row_of = np.repeat(np.arange(n), spans)
        if len(v_flat):
            out_flat[bases[row_of[keep]] + i_flat[keep]] = v_flat[keep]
        dense = _wrap_list(pa.array(out_flat), dense_counts, valid)
        return done(pa.StructArray.from_arrays(
            [dense, shape_col.cast(pa.large_list(pa.uint64()))],
            ["data", "shape"],
            mask=pa.array(~valid) if not valid.all() else None))

    return None


_FNV_OFFSET = np.uint64(14695981039346656037)
_FNV_PRIME = np.uint64(1099511628211)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
        x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)).astype(np.uint64)
        x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)).astype(np.uint64)
        return (x ^ (x >> np.uint64(31))).astype(np.uint64)


def _hash_array(s: Series) -> np.ndarray:
    n = len(s)
    if s.is_pyobject():
        return np.array([np.uint64(hash(repr(v)) & 0xFFFFFFFFFFFFFFFF)
                         for v in s._pyobjs], dtype=np.uint64)
    arr = s.to_arrow()
    dt = s.dtype
    valid = np.asarray(pc.is_valid(arr).to_numpy(zero_copy_only=False), dtype=np.bool_)
    if dt.is_null():
        # every row is null → the null sentinel directly (the generic path
        # would try to reinterpret an object-dtype numpy array)
        return np.full(n, np.uint64(0x6E756C6C), dtype=np.uint64)
    if dt.is_string() or dt.is_binary():
        enc = arr.cast(pa.large_binary())
        buffers = enc.buffers()
        offsets = np.frombuffer(buffers[1], dtype=np.int64,
                                count=len(enc) + 1, offset=enc.offset * 8)
        data = np.frombuffer(buffers[2], dtype=np.uint8) if buffers[2] is not None \
            else np.empty(0, dtype=np.uint8)
        from . import native
        if native.AVAILABLE:
            # C++ xxh64 per row (reference hash.rs path is native too)
            out = native.hash_var(offsets, data, valid)
            out[~valid] = np.uint64(0x6E756C6C)
            return out
        # numpy fallback: vectorized FNV-1a over the flat byte buffer
        out = np.full(n, _FNV_OFFSET, dtype=np.uint64)
        lengths = offsets[1:] - offsets[:-1]
        maxlen = int(lengths.max()) if n else 0
        with np.errstate(over="ignore"):
            for i in range(maxlen):
                sel = lengths > i
                idx = offsets[:-1][sel] + i
                out[sel] = (out[sel] ^ data[idx].astype(np.uint64)) * _FNV_PRIME
    else:
        phys = dt.to_physical()
        rep = phys.device_repr()
        if rep is None:
            return np.array([np.uint64(hash(repr(v)) & 0xFFFFFFFFFFFFFFFF)
                             for v in arr.to_pylist()], dtype=np.uint64)
        sp = s if phys == dt else s.cast(phys)
        if valid.all():
            vals = sp.to_numpy()
        else:
            # a null mask must not change VALID rows' hashes: numpy
            # promotes a nullable int/bool column to float64 (or object),
            # so `5` used to hash by its FLOAT bit pattern beside a null
            # but by its int bits in a dense column — two join/group
            # sides with different masks were silently NOT co-partitioned
            # (missed matches under the spill-partitioned join). Fill
            # nulls with a typed zero so the numpy round trip keeps the
            # true physical dtype; the sentinel overwrite below restores
            # the null rows.
            a = sp.to_arrow()
            try:
                fill = pa.scalar(
                    False if pa.types.is_boolean(a.type) else 0,
                    type=a.type)
                vals = pc.fill_null(a, fill).to_numpy(
                    zero_copy_only=False)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError,
                    TypeError):
                vals = sp.to_numpy()
        vals = np.ascontiguousarray(np.nan_to_num(vals) if vals.dtype.kind == "f" else vals)
        if vals.dtype.kind == "O":  # mixed/unfillable → repr-hash rows
            out = np.array([np.uint64(hash(repr(v)) & 0xFFFFFFFFFFFFFFFF)
                            for v in vals], dtype=np.uint64)
            out[~valid] = np.uint64(0x6E756C6C)
            return out
        if vals.dtype.itemsize <= 8:
            as_u64 = np.zeros(n, dtype=np.uint64)
            as_u64[:] = vals.view(
                {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}[vals.dtype.itemsize]
            ).astype(np.uint64)
            out = _splitmix64(as_u64)
        else:
            out = np.array([np.uint64(hash(v) & 0xFFFFFFFFFFFFFFFF)
                            for v in vals], dtype=np.uint64)
    out[~valid] = np.uint64(0x6E756C6C)  # b"null"
    return out


_XXH_P1 = 11400714785074694791
_XXH_P2 = 14029467366897019727
_XXH_P3 = 1609587929392839161
_XXH_P4 = 9650029242287828579
_XXH_P5 = 2870177450012600261
_U64 = (1 << 64) - 1


def _rotl64(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _U64


def _xxh64_py(data: bytes, seed: int) -> int:
    """Pure-python XXH64 — bit-identical to the native kernel's xxh64
    (``daft_tpu/native/src/kernels.cpp``) so fallback and native minhash
    signatures are comparable across a mixed fleet."""
    n, i = len(data), 0
    if n >= 32:
        v1 = (seed + _XXH_P1 + _XXH_P2) & _U64
        v2 = (seed + _XXH_P2) & _U64
        v3 = seed & _U64
        v4 = (seed - _XXH_P1) & _U64
        while i <= n - 32:
            v1 = (_rotl64((v1 + int.from_bytes(data[i:i+8], "little")
                           * _XXH_P2) & _U64, 31) * _XXH_P1) & _U64
            v2 = (_rotl64((v2 + int.from_bytes(data[i+8:i+16], "little")
                           * _XXH_P2) & _U64, 31) * _XXH_P1) & _U64
            v3 = (_rotl64((v3 + int.from_bytes(data[i+16:i+24], "little")
                           * _XXH_P2) & _U64, 31) * _XXH_P1) & _U64
            v4 = (_rotl64((v4 + int.from_bytes(data[i+24:i+32], "little")
                           * _XXH_P2) & _U64, 31) * _XXH_P1) & _U64
            i += 32
        h = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12)
             + _rotl64(v4, 18)) & _U64
        for v in (v1, v2, v3, v4):
            h ^= (_rotl64((v * _XXH_P2) & _U64, 31) * _XXH_P1) & _U64
            h = ((h * _XXH_P1) + _XXH_P4) & _U64
    else:
        h = (seed + _XXH_P5) & _U64
    h = (h + n) & _U64
    while i + 8 <= n:
        k = (_rotl64((int.from_bytes(data[i:i+8], "little") * _XXH_P2) & _U64,
                     31) * _XXH_P1) & _U64
        h = ((_rotl64(h ^ k, 27) * _XXH_P1) + _XXH_P4) & _U64
        i += 8
    if i + 4 <= n:
        h ^= (int.from_bytes(data[i:i+4], "little") * _XXH_P1) & _U64
        h = ((_rotl64(h, 23) * _XXH_P2) + _XXH_P3) & _U64
        i += 4
    while i < n:
        h ^= (data[i] * _XXH_P5) & _U64
        h = (_rotl64(h, 11) * _XXH_P1) & _U64
        i += 1
    h ^= h >> 33
    h = (h * _XXH_P2) & _U64
    h ^= h >> 29
    h = (h * _XXH_P3) & _U64
    h ^= h >> 32
    return h


def _minhash_fallback(values, num_hashes: int, ngram_size: int,
                      seed: int) -> np.ndarray:
    """Pure-python minhash, bit-identical to the native ``dn_minhash`` kernel:
    same xorshift permutation coefficients, same ASCII-whitespace word split,
    and the same xxh64(seed=42) over the raw byte span of each shingle
    (original separators included) — so signatures from native and fallback
    workers compare correctly."""
    p = (1 << 61) - 1
    st = seed or 1
    def nxt():
        nonlocal st
        st ^= (st << 13) & _U64
        st ^= st >> 7
        st ^= (st << 17) & _U64
        return st
    # interleaved draws, matching the native kernel's per-j (a, b) order
    a, b = [], []
    for _ in range(num_hashes):
        a.append(nxt() % (p - 1) + 1)
        b.append(nxt() % p)
    ws = (0x20, 0x09, 0x0A, 0x0D)
    out = np.full((len(values), num_hashes), 0xFFFFFFFF, dtype=np.uint32)
    for i, v in enumerate(values):
        if v is None:
            continue
        raw = v.encode("utf-8") if isinstance(v, str) else bytes(v)
        starts, ends = [], []
        w = -1
        for k, byte in enumerate(raw):
            is_ws = byte in ws
            if not is_ws and w < 0:
                w = k
            if is_ws and w >= 0:
                starts.append(w)
                ends.append(k)
                w = -1
        if w >= 0:
            starts.append(w)
            ends.append(len(raw))
        nwords = len(starts)
        if nwords == 0:
            continue
        nsh = max(1, nwords - ngram_size + 1)
        for s in range(nsh):
            last = min(s + ngram_size, nwords) - 1
            hv = _xxh64_py(raw[starts[s]:ends[last]], 42) & p
            for j in range(num_hashes):
                ph = (a[j] * hv + b[j]) % p
                val = ph & 0xFFFFFFFF
                if val < out[i, j]:
                    out[i, j] = val
    return out
