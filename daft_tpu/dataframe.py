"""DataFrame: the lazy user-facing API.

Reference: ``daft/dataframe/dataframe.py:108`` (the ~100-method DataFrame
class). Each method extends the logical plan via LogicalPlanBuilder; execution
happens on collect/show/iteration through the context's runner.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

from .context import get_context
from .datatype import DataType
from .expressions import Expression, col, lit
from .logical.builder import LogicalPlanBuilder
from .micropartition import MicroPartition
from .recordbatch import RecordBatch
from .runners.runner import PartitionSet
from .schema import Schema

ColumnInput = Union[str, Expression]

_range = range  # the module-level `range` below (daft.range) shadows the builtin


class DataFrame:
    def __init__(self, builder: LogicalPlanBuilder):
        self._builder = builder
        self._result: Optional[PartitionSet] = None
        self._stats = None  # RuntimeStatsContext captured at collect()

    # ---- meta ------------------------------------------------------------
    @property
    def builder(self) -> LogicalPlanBuilder:
        return self._builder

    def schema(self) -> Schema:
        return self._builder.schema()

    @property
    def column_names(self) -> List[str]:
        return self._builder.schema().column_names

    @property
    def columns(self) -> List[Expression]:
        return [col(n) for n in self.column_names]

    def __contains__(self, name: str) -> bool:
        return name in self._builder.schema()

    def __getitem__(self, key) -> Expression:
        if isinstance(key, str):
            if key != "*" and key not in self._builder.schema():
                raise ValueError(f"unknown column {key!r}")
            return col(key)
        if isinstance(key, int):
            return col(self.column_names[key])
        raise TypeError(f"cannot index DataFrame with {key!r}")

    def explain(self, show_all: bool = False, analyze: bool = False) -> None:
        """Print query plans; ``analyze=True`` executes the query and prints
        the physical plan annotated with per-operator rows/time (reference:
        AQE ``explain_analyze``, ``physical_planner/planner.rs:614``)."""
        if analyze:
            self.collect()
            print("== Physical Plan (analyzed) ==")
            if self._stats is not None:
                print(self._stats.render())
            else:
                print("(no runtime stats recorded for this query)")
            return
        print("== Unoptimized Logical Plan ==")
        print(self._builder.repr_ascii())
        if show_all:
            print("\n== Optimized Logical Plan ==")
            print(self._builder.optimize().repr_ascii())

    def num_partitions(self) -> int:
        return self._builder.plan.num_partitions()

    def __repr__(self):
        if self._result is not None:
            return self._preview_str()
        return f"DataFrame({self.schema()!r})\n(unmaterialized — call .collect() or .show())"

    # ---- transformations -------------------------------------------------
    def select(self, *columns: ColumnInput) -> "DataFrame":
        rewritten, hoisted = _hoist_nested_windows(columns)
        if hoisted:
            # a window nested inside a scalar expression (e.g.
            # ``x * 100 / SUM(x) OVER (...)``) computes in its own Window
            # plan node first, then the outer expression reads the temp
            # column (reference: ExtractWindowFunction optimizer rule)
            wdf = self.with_columns(hoisted)
            return DataFrame(wdf.select(*rewritten)._builder)
        win = [c for c in columns if isinstance(c, Expression)
               and c._unalias().op == "window"]
        if win:
            # route window exprs through a Window plan node, then project
            wdf = self.with_columns({e.name(): e for e in win})
            keep = [col(c.name()) if (isinstance(c, Expression)
                                      and c._unalias().op == "window") else c
                    for c in columns]
            return DataFrame(wdf._builder.select(keep))
        return DataFrame(self._builder.select(list(columns)))

    def with_column(self, name: str, expr: Expression) -> "DataFrame":
        return self.with_columns({name: expr})

    def with_columns(self, columns: Dict[str, Expression]) -> "DataFrame":
        exprs = [e.alias(n) for n, e in columns.items()]
        window_exprs = [e for e in exprs if e._unalias().op == "window"]
        if window_exprs:
            plain = [e for e in exprs if e._unalias().op != "window"]
            b = self._builder
            if plain:
                b = b.with_columns(plain)
            # one Window plan node per distinct spec, chained (reference:
            # ExtractWindowFunction groups by WindowSpec the same way)
            by_spec = {}
            for e in window_exprs:
                by_spec.setdefault(repr(e._unalias().params[0]), []).append(e)
            for group in by_spec.values():
                w = group[0]._unalias().params[0]
                b = b.window(group, w._partition_by, w._order_by,
                             w._descending, w._nulls_first, w._frame)
            return DataFrame(b)
        return DataFrame(self._builder.with_columns(exprs))

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        return DataFrame(self._builder.with_columns_renamed({old: new}))

    def with_columns_renamed(self, mapping: Dict[str, str]) -> "DataFrame":
        return DataFrame(self._builder.with_columns_renamed(mapping))

    def exclude(self, *names: str) -> "DataFrame":
        return DataFrame(self._builder.exclude(list(names)))

    def filter(self, predicate: Union[Expression, str]) -> "DataFrame":
        """Alias of :meth:`where` (reference has both)."""
        return self.where(predicate)

    def where(self, predicate: Union[Expression, str]) -> "DataFrame":
        if isinstance(predicate, str):
            from .sql import sql_expr
            predicate = sql_expr(predicate)
        return DataFrame(self._builder.filter(predicate))

    filter = where

    def limit(self, n: int, offset: int = 0) -> "DataFrame":
        return DataFrame(self._builder.limit(n, offset))

    def offset(self, n: int) -> "DataFrame":
        return DataFrame(self._builder.limit(2 ** 62, n))

    def head(self, n: int = 10) -> "DataFrame":
        return self.limit(n)

    def explode(self, *columns: ColumnInput) -> "DataFrame":
        return DataFrame(self._builder.explode(list(columns)))

    def unpivot(self, ids, values=None, variable_name: str = "variable",
                value_name: str = "value") -> "DataFrame":
        ids = ids if isinstance(ids, (list, tuple)) else [ids]
        values = values if values is None or isinstance(values, (list, tuple)) \
            else [values]
        return DataFrame(self._builder.unpivot(ids, values, variable_name,
                                               value_name))

    melt = unpivot

    def sort(self, by, desc: Union[bool, List[bool]] = False,
             nulls_first=None) -> "DataFrame":
        by = by if isinstance(by, (list, tuple)) else [by]
        return DataFrame(self._builder.sort(by, desc, nulls_first))

    def distinct(self, *on: ColumnInput) -> "DataFrame":
        return DataFrame(self._builder.distinct(list(on) if on else None))

    unique = distinct

    def _drop_where(self, cols, default_names, term_of) -> "DataFrame":
        names = [c.name() for c in _flatten_cols(cols)] or default_names
        pred = None
        for n in names:
            term = term_of(n)
            pred = term if pred is None else pred & term
        return self if pred is None else self.where(pred)

    def drop_nan(self, *cols: ColumnInput) -> "DataFrame":
        """Drop rows where any of ``cols`` (default: all float columns) is
        NaN — nulls survive (reference: ``DataFrame.drop_nan``)."""
        return self._drop_where(
            cols, [f.name for f in self.schema() if f.dtype.is_floating()],
            lambda n: ~col(n).float.is_nan() | col(n).is_null())

    def drop_null(self, *cols: ColumnInput) -> "DataFrame":
        """Drop rows where any of ``cols`` (default: all columns) is null
        (reference: ``DataFrame.drop_null``)."""
        return self._drop_where(cols, self.column_names,
                                lambda n: col(n).not_null())

    def pipe(self, func, *args, **kwargs):
        """``df.pipe(f, ...)`` → ``f(df, ...)`` (reference parity)."""
        return func(self, *args, **kwargs)

    def drop_duplicates(self, *on) -> "DataFrame":
        return self.distinct(*on)

    def sample(self, fraction: Optional[float] = None,
               size: Optional[int] = None, with_replacement: bool = False,
               seed: Optional[int] = None) -> "DataFrame":
        return DataFrame(self._builder.sample(fraction, size,
                                              with_replacement, seed))

    def repartition(self, num: Optional[int], *cols: ColumnInput) -> "DataFrame":
        if cols:
            return DataFrame(self._builder.hash_repartition(num, list(cols)))
        return DataFrame(self._builder.random_shuffle(num))

    def into_partitions(self, num: int) -> "DataFrame":
        return DataFrame(self._builder.into_partitions(num))

    def concat(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._builder.concat(other._builder))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._builder.union(other._builder, all=False))

    def union_all(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._builder.union(other._builder, all=True))

    def _aligned_by_name(self, other: "DataFrame") -> "DataFrame":
        mine, theirs = self.column_names, other.column_names
        if set(mine) != set(theirs):
            raise ValueError(
                f"union_by_name: column sets differ "
                f"({sorted(set(mine) ^ set(theirs))})")
        return other.select(*[col(n) for n in mine])

    def union_by_name(self, other: "DataFrame") -> "DataFrame":
        """Set union matching columns BY NAME, order-independent
        (reference: ``DataFrame.union_by_name``)."""
        return self.union(self._aligned_by_name(other))

    def union_all_by_name(self, other: "DataFrame") -> "DataFrame":
        return self.union_all(self._aligned_by_name(other))

    def intersect(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._builder.intersect(other._builder))

    def intersect_all(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._builder.intersect(other._builder, all=True))

    def except_distinct(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._builder.except_(other._builder))

    def except_all(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._builder.except_(other._builder, all=True))

    def join(self, other: "DataFrame",
             on: Optional[Union[ColumnInput, List[ColumnInput]]] = None,
             left_on=None, right_on=None, how: str = "inner",
             strategy: Optional[str] = None, prefix: Optional[str] = None,
             suffix: Optional[str] = None) -> "DataFrame":
        if on is not None:
            left_on = right_on = on
        if how != "cross" and left_on is None:
            raise ValueError("join requires `on` or `left_on`/`right_on`")
        lo = left_on if isinstance(left_on, (list, tuple)) else [left_on]
        ro = right_on if isinstance(right_on, (list, tuple)) else [right_on]
        if how == "cross":
            lo, ro = [], []
        return DataFrame(self._builder.join(other._builder, lo, ro, how,
                                            strategy, prefix, suffix))

    def pivot(self, group_by, pivot_col, value_col, agg_fn: str,
              names: Optional[List[str]] = None) -> "DataFrame":
        gb = group_by if isinstance(group_by, (list, tuple)) else [group_by]
        return DataFrame(self._builder.pivot(gb, pivot_col, value_col,
                                             agg_fn, names))

    def add_monotonically_increasing_id(self, column_name=None) -> "DataFrame":
        return DataFrame(
            self._builder.add_monotonically_increasing_id(column_name))

    def transform(self, func, *args, **kwargs) -> "DataFrame":
        out = func(self, *args, **kwargs)
        assert isinstance(out, DataFrame)
        return out

    # ---- aggregations ----------------------------------------------------
    def agg(self, *to_agg) -> "DataFrame":
        exprs = _flatten_exprs(to_agg)
        return DataFrame(self._builder.aggregate(exprs, []))

    def groupby(self, *group_by: ColumnInput) -> "GroupedDataFrame":
        return GroupedDataFrame(self, _flatten_cols(group_by))

    group_by = groupby

    def _agg_all(self, op: str) -> "DataFrame":
        exprs = []
        for f in self.schema():
            e = getattr(col(f.name), op, None)
            if e is None:
                continue
            try:
                agg_e = e()
                agg_e.to_field(self.schema())
                exprs.append(agg_e)
            except Exception:
                continue
        return DataFrame(self._builder.aggregate(exprs, []))

    def sum(self, *cols: ColumnInput) -> "DataFrame":
        if not cols:
            return self._agg_all("sum")
        return self.agg(*[_c(c).sum() for c in _flatten_cols(cols)])

    def mean(self, *cols: ColumnInput) -> "DataFrame":
        if not cols:
            return self._agg_all("mean")
        return self.agg(*[_c(c).mean() for c in _flatten_cols(cols)])

    def min(self, *cols):
        if not cols:
            return self._agg_all("min")
        return self.agg(*[_c(c).min() for c in _flatten_cols(cols)])

    def max(self, *cols):
        if not cols:
            return self._agg_all("max")
        return self.agg(*[_c(c).max() for c in _flatten_cols(cols)])

    def any_value(self, *cols):
        return self.agg(*[_c(c).any_value() for c in _flatten_cols(cols)])

    def count(self, *cols) -> "DataFrame":
        if not cols:
            return self.agg(lit(1).count("all").alias("count"))
        return self.agg(*[_c(c).count() for c in _flatten_cols(cols)])

    def agg_list(self, *cols):
        return self.agg(*[_c(c).agg_list() for c in _flatten_cols(cols)])

    def agg_concat(self, *cols):
        return self.agg(*[_c(c).agg_concat() for c in _flatten_cols(cols)])

    def agg_set(self, *cols):
        return self.agg(*[_c(c).agg_set() for c in _flatten_cols(cols)])

    def stddev(self, *cols):
        return self.agg(*[_c(c).stddev() for c in _flatten_cols(cols)])

    def count_rows(self) -> int:
        d = self.count().to_pydict()
        return int(d["count"][0])

    def __len__(self) -> int:
        if self._result is not None:
            return len(self._result)
        return self.count_rows()

    def describe(self) -> "DataFrame":
        """Summary stats per column (reference: dataframe.describe)."""
        aggs = []
        for f in self.schema():
            c = col(f.name)
            aggs.append(c.count().cast(DataType.uint64()).alias(f"{f.name}_count"))
            aggs.append(c.count_distinct().alias(f"{f.name}_unique"))
            if f.dtype.is_numeric():
                aggs.append(c.mean().alias(f"{f.name}_mean"))
                aggs.append(c.min().alias(f"{f.name}_min"))
                aggs.append(c.max().alias(f"{f.name}_max"))
        return DataFrame(self._builder.aggregate(aggs, []))

    def summarize(self) -> "DataFrame":
        return self.describe()

    # ---- writes ----------------------------------------------------------
    def write_parquet(self, root_dir: str, compression: str = "snappy",
                      write_mode: str = "append", partition_cols=None,
                      io_config=None) -> "DataFrame":
        return self._write("parquet", root_dir, write_mode, partition_cols,
                           {"compression": compression})

    def write_csv(self, root_dir: str, write_mode: str = "append",
                  partition_cols=None, io_config=None) -> "DataFrame":
        return self._write("csv", root_dir, write_mode, partition_cols, {})

    def write_json(self, root_dir: str, write_mode: str = "append",
                   partition_cols=None, io_config=None) -> "DataFrame":
        return self._write("json", root_dir, write_mode, partition_cols, {})

    def _write(self, kind, root_dir, mode, partition_cols, options):
        pc_list = None
        if partition_cols is not None:
            pc_list = partition_cols if isinstance(partition_cols, (list, tuple)) \
                else [partition_cols]
        b = self._builder.table_write(kind, root_dir, pc_list, mode, options)
        out = DataFrame(b)
        return out.collect()

    def write_sink(self, sink) -> "DataFrame":
        out = DataFrame(self._builder.write_sink(sink))
        return out.collect()

    def write_deltalake(self, table_uri: str, mode: str = "append",
                        io_config=None) -> "DataFrame":
        """Commit as a Delta Lake transaction (reference:
        ``DataFrame.write_deltalake``; native log writer in io/delta.py)."""
        from .io.delta import write_deltalake as _w
        _w(self, table_uri, mode=mode, io_config=io_config)
        return self

    def write_iceberg(self, table_uri: str, mode: str = "append",
                      io_config=None) -> "DataFrame":
        """Commit as an Apache Iceberg snapshot (reference:
        ``DataFrame.write_iceberg``; native v1 writer in io/iceberg.py)."""
        from .io.iceberg import write_iceberg as _w
        _w(self, table_uri, mode=mode, io_config=io_config)
        return self

    # ---- execution -------------------------------------------------------
    def collect(self, num_preview_rows: Optional[int] = 8) -> "DataFrame":
        if self._result is None:
            from . import observability as obs
            runner = get_context().get_or_create_runner()
            self._result = runner.run(self._builder)
            self._stats = obs.last_query_stats()
            # downstream queries read from the materialized result
            self._builder = LogicalPlanBuilder.from_in_memory(
                self._result.partitions, self._result.schema)
        return self

    def _materialize(self) -> PartitionSet:
        self.collect()
        return self._result

    def iter_partitions(self) -> Iterator[MicroPartition]:
        if self._result is not None:
            yield from self._result.partitions
            return
        runner = get_context().get_or_create_runner()
        yield from runner.run_iter(self._builder)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for p in self.iter_partitions():
            for b in p.batches():
                cols = {c.name(): c.to_pylist() for c in b.columns()}
                for i in _range(len(b)):
                    yield {k: v[i] for k, v in cols.items()}

    def __iter__(self):
        return self.iter_rows()

    def show(self, n: int = 8) -> None:
        rows = self.limit(n)._materialize().to_recordbatch()
        print(rows.to_pandas().to_string())

    def _preview_str(self) -> str:
        rb = self._result.to_recordbatch()
        pdf = rb.head(8).to_pandas()
        return f"{pdf}\n({len(rb)} rows)"

    # ---- conversions -----------------------------------------------------
    def to_pydict(self) -> Dict[str, list]:
        return self._materialize().to_recordbatch().to_pydict()

    def to_pylist(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def to_arrow(self) -> pa.Table:
        return self._materialize().to_recordbatch().to_arrow_table()

    def to_pandas(self):
        return self._materialize().to_recordbatch().to_pandas()

    def to_torch_map_dataset(self):
        from .to_torch import TorchMapDataset
        return TorchMapDataset(self)

    def to_torch_iter_dataset(self):
        from .to_torch import TorchIterDataset
        return TorchIterDataset(self)

    def to_arrow_iter(self) -> Iterator[pa.RecordBatch]:
        """Stream results as Arrow record batches without materializing a
        combined copy per partition (reference:
        ``DataFrame.to_arrow_iter``)."""
        for p in self.iter_partitions():
            for rb in p.batches():
                yield from rb.to_arrow_table().to_batches()

    def to_ray_dataset(self):
        """Bridge to a Ray Dataset (reference: RayRunnerIO.to_ray_dataset;
        needs the optional 'ray' package)."""
        try:
            import ray.data
        except ImportError as exc:
            raise ImportError("to_ray_dataset requires the optional 'ray' "
                              "package") from exc
        return ray.data.from_arrow(self.to_arrow())

    def to_dask_dataframe(self):
        """Bridge to a Dask DataFrame (reference: RayRunnerIO
        .to_dask_dataframe; needs the optional 'dask' package)."""
        try:
            import dask.dataframe as dd
        except ImportError as exc:
            raise ImportError("to_dask_dataframe requires the optional "
                              "'dask' package") from exc
        return dd.from_pandas(self.to_pandas(),
                              npartitions=max(self.num_partitions(), 1))

    def write_lance(self, uri: str, mode: str = "create",
                    io_config=None):
        """Write as a Lance dataset version (reference:
        ``DataFrame.write_lance`` over the lance SDK; implemented natively
        — versioned column-page datasets, ``io/lance.py``)."""
        from .io.lance import write_lance as _impl
        _impl(self, uri, mode=mode, io_config=io_config)
        return self


class GroupedDataFrame:
    """Reference: ``daft/dataframe/dataframe.py`` GroupedDataFrame."""

    def __init__(self, df: DataFrame, group_by: List[Expression]):
        self.df = df
        self.group_by = group_by

    def agg(self, *to_agg) -> DataFrame:
        exprs = _flatten_exprs(to_agg)
        return DataFrame(self.df._builder.aggregate(exprs, self.group_by))

    def _agg_all(self, op: str) -> DataFrame:
        gb_names = {e.name() for e in self.group_by}
        exprs = []
        for f in self.df.schema():
            if f.name in gb_names:
                continue
            try:
                e = getattr(col(f.name), op)()
                e.to_field(self.df.schema())
                exprs.append(e)
            except Exception:
                continue
        return DataFrame(self.df._builder.aggregate(exprs, self.group_by))

    def sum(self, *cols):
        if not cols:
            return self._agg_all("sum")
        return self.agg(*[_c(c).sum() for c in _flatten_cols(cols)])

    def mean(self, *cols):
        if not cols:
            return self._agg_all("mean")
        return self.agg(*[_c(c).mean() for c in _flatten_cols(cols)])

    def min(self, *cols):
        if not cols:
            return self._agg_all("min")
        return self.agg(*[_c(c).min() for c in _flatten_cols(cols)])

    def max(self, *cols):
        if not cols:
            return self._agg_all("max")
        return self.agg(*[_c(c).max() for c in _flatten_cols(cols)])

    def any_value(self, *cols):
        return self.agg(*[_c(c).any_value() for c in _flatten_cols(cols)])

    def count(self, *cols):
        if not cols:
            gb_names = {e.name() for e in self.group_by}
            exprs = [col(f.name).count() for f in self.df.schema()
                     if f.name not in gb_names]
            return self.agg(*exprs)
        return self.agg(*[_c(c).count() for c in _flatten_cols(cols)])

    def agg_list(self, *cols):
        return self.agg(*[_c(c).agg_list() for c in _flatten_cols(cols)])

    def agg_concat(self, *cols):
        return self.agg(*[_c(c).agg_concat() for c in _flatten_cols(cols)])

    def agg_set(self, *cols):
        return self.agg(*[_c(c).agg_set() for c in _flatten_cols(cols)])

    def stddev(self, *cols):
        return self.agg(*[_c(c).stddev() for c in _flatten_cols(cols)])

    def map_groups(self, udf_expr: Expression) -> DataFrame:
        raise NotImplementedError("map_groups lands with the UDF actor pools")


def _c(x: ColumnInput) -> Expression:
    return col(x) if isinstance(x, str) else x


def _flatten_cols(cols) -> List[Expression]:
    out = []
    for c in cols:
        if isinstance(c, (list, tuple)):
            out.extend(_c(x) for x in c)
        else:
            out.append(_c(c))
    return out


def _flatten_exprs(to_agg) -> List[Expression]:
    out = []
    for a in to_agg:
        if isinstance(a, (list, tuple)):
            out.extend(a)
        else:
            out.append(a)
    return out


# ---------------------------------------------------------------------------
# constructors (daft.from_* family)

def _hoist_nested_windows(columns):
    """Hoist OVER() subtrees buried inside scalar expressions into temp
    columns (reference: ``ExtractWindowFunction`` rule). Top-level window
    expressions are left alone — select's existing Window routing handles
    them. → (rewritten columns, {temp name: window expr})."""
    hoisted: Dict[str, Expression] = {}

    def walk(e: Expression, top: bool) -> Expression:
        inner = e._unalias()
        if inner.op == "window":
            if top:
                return e
            name = f"__win_h{len(hoisted)}"
            hoisted[name] = inner
            return col(name)
        new_args = tuple(walk(c, False) for c in e.args)
        # identity compare: Expression.__eq__ builds an eq-expression
        if all(a is b for a, b in zip(new_args, e.args)):
            return e
        return e.with_children(new_args)

    out = [walk(c, True) if isinstance(c, Expression) else c
           for c in columns]
    return out, hoisted


def from_pydict(data: Dict[str, Any]) -> DataFrame:
    mp = MicroPartition.from_pydict(data)
    return DataFrame(LogicalPlanBuilder.from_in_memory([mp], mp.schema))


def from_pylist(rows: List[Dict[str, Any]]) -> DataFrame:
    keys: List[str] = []
    for r in rows:
        for k in r:
            if k not in keys:
                keys.append(k)
    return from_pydict({k: [r.get(k) for r in rows] for k in keys})


def from_arrow(t) -> DataFrame:
    if isinstance(t, pa.RecordBatch):
        t = pa.Table.from_batches([t])
    mp = MicroPartition.from_arrow_table(t)
    return DataFrame(LogicalPlanBuilder.from_in_memory([mp], mp.schema))


def from_pandas(pdf) -> DataFrame:
    return from_arrow(pa.Table.from_pandas(pdf, preserve_index=False))


def from_glob_path(path: str) -> DataFrame:
    """List files matching a glob as a DataFrame (reference: from_glob_path)."""
    import os
    from .io.scan import glob_paths
    paths = glob_paths(path)
    sizes = [os.path.getsize(p) if os.path.exists(p) else None for p in paths]
    import datetime
    rows = {"path": paths, "size": sizes,
            "num_rows": [None] * len(paths)}
    return from_pydict(rows)


def range(start: int, end: Optional[int] = None, step: int = 1,
          partitions: int = 1) -> DataFrame:
    if end is None:
        start, end = 0, start
    df = from_pydict({"id": np.arange(start, end, step)})
    if partitions > 1:
        df = df.into_partitions(partitions)
    return df
