"""Embedded Spark Connect gRPC server.

Reference: ``src/daft-connect/src/connect_service.rs:235-334`` — a tonic
``SparkConnectService`` whose ``execute_plan`` / ``analyze_plan`` / ``config``
translate Spark protos through the engine and stream Arrow batches back. Here
the service is built on grpc's generic method handlers against the
wire-compatible subset protos (``spark_connect_subset.proto``), so a Spark
Connect client can point at ``sc://host:port`` and run queries on daft_tpu.

Usage::

    from daft_tpu.connect import start_server
    server = start_server()           # SparkConnectServer, .port/.address
    ...
    server.stop()
"""

from __future__ import annotations

import io
import threading
import time
import uuid
from typing import Dict, Iterator, Optional

import pyarrow as pa

from . import spark_connect_subset_pb2 as pb
from .analyzer import (SparkAnalyzer, Unsupported, dtype_to_proto, parse_ddl,
                       schema_to_proto)

_SERVICE = "spark.connect.SparkConnectService"
_VERSION = "3.5.1+daft-tpu"

# rows per streamed ArrowBatch message (Spark chunks large results the same
# way; grpc messages default-cap at 4MB)
_BATCH_ROWS = 1 << 16


class _Operation:
    """Lifecycle record for one ExecutePlan.

    Responses are buffered ONLY for reattachable executions (the client
    opted in via ``ReattachOptions`` — Spark's own rule; buffering every
    plain execute would pin each query's whole result in session RAM),
    retained until the client RELEASES them, so a dropped connection can
    REATTACH and resume from its last response id. INTERRUPT flips the
    cancel flag, honored between streamed batches (a batch mid-kernel
    finishes). A failure is recorded as (code, message) and re-raised to
    reattaching clients — a truncated replay that ends cleanly would read
    as a complete result."""

    def __init__(self, op_id: str, tags, reattachable: bool):
        self.op_id = op_id
        self.tags = set(tags or ())
        self.reattachable = reattachable
        self.cancel = threading.Event()
        self.done = threading.Event()
        self.cond = threading.Condition()
        self.buffer: list = []          # ExecutePlanResponse, in order
        self.base = 0                   # absolute index of buffer[0] —
        #                                 released prefixes are DELETED
        #                                 (a release must actually free
        #                                 the acknowledged bytes)
        self.error = None               # (grpc code, message) on failure
        self.finished_at: Optional[float] = None  # monotonic; sweep clock
        self.retained_bytes = 0         # serialized bytes in self.buffer
        self._cancel_cbs: list = []     # real cancellation hooks (the
        #                                 serving scheduler's handle)

    def bind_cancel(self, fn) -> None:
        """Register a callback fired on INTERRUPT — wires the client's
        cancel through to the scheduler's cooperative CancelToken so a
        running query actually unwinds (not just the response stream)."""
        fire_now = False
        with self.cond:
            if self.cancel.is_set():
                fire_now = True
            else:
                self._cancel_cbs.append(fn)
        if fire_now:
            try:
                fn()
            except Exception:
                pass

    def request_cancel(self) -> None:
        with self.cond:
            self.cancel.set()
            cbs = list(self._cancel_cbs)
        for fn in cbs:
            try:
                fn()
            except Exception:
                pass

    def record(self, r) -> None:
        if not self.reattachable:
            return
        with self.cond:
            self.buffer.append(r)
            self.retained_bytes += r.ByteSize()
            self.cond.notify_all()

    def finish(self, error=None) -> None:
        with self.cond:
            if error is not None and self.error is None:
                self.error = error
            if self.finished_at is None:
                self.finished_at = time.monotonic()
            self.done.set()
            self.cond.notify_all()

    def total(self) -> int:
        """Absolute count of responses produced so far."""
        return self.base + len(self.buffer)

    def after(self, last_response_id: Optional[str]):
        """(responses after the given id — all retained when None, the
        released prefix is gone), absolute high-water mark."""
        with self.cond:
            start = self.base
            if last_response_id:
                for i in range(len(self.buffer) - 1, -1, -1):
                    if self.buffer[i].response_id == last_response_id:
                        start = self.base + i + 1
                        break
            return list(self.buffer[start - self.base:]), self.total()

    def release_until(self, response_id: str) -> None:
        with self.cond:
            for i, r in enumerate(self.buffer):
                if r.response_id == response_id:
                    self.retained_bytes -= sum(
                        b.ByteSize() for b in self.buffer[:i + 1])
                    del self.buffer[:i + 1]
                    self.base += i + 1
                    break


class _SessionState:
    def __init__(self):
        self.config: Dict[str, str] = {}
        self.views: Dict[str, object] = {}
        self.artifacts: Dict[str, bytes] = {}
        self.operations: Dict[str, _Operation] = {}
        self.server_side_id = uuid.uuid4().hex

    @property
    def analyzer(self) -> SparkAnalyzer:
        return SparkAnalyzer(self.views)


class SparkConnectServer:
    """grpc server exposing daft_tpu as a Spark Connect endpoint."""

    def __init__(self, port: int = 0, max_workers: int = 8):
        import concurrent.futures as cf

        import grpc

        self._grpc = grpc
        self._sessions: Dict[str, _SessionState] = {}
        self._lock = threading.Lock()

        handlers = {
            "ExecutePlan": grpc.unary_stream_rpc_method_handler(
                self._execute_plan,
                request_deserializer=pb.ExecutePlanRequest.FromString,
                response_serializer=pb.ExecutePlanResponse.SerializeToString),
            "AnalyzePlan": grpc.unary_unary_rpc_method_handler(
                self._analyze_plan,
                request_deserializer=pb.AnalyzePlanRequest.FromString,
                response_serializer=pb.AnalyzePlanResponse.SerializeToString),
            "Config": grpc.unary_unary_rpc_method_handler(
                self._config,
                request_deserializer=pb.ConfigRequest.FromString,
                response_serializer=pb.ConfigResponse.SerializeToString),
            "AddArtifacts": grpc.stream_unary_rpc_method_handler(
                self._add_artifacts,
                request_deserializer=pb.AddArtifactsRequest.FromString,
                response_serializer=(
                    pb.AddArtifactsResponse.SerializeToString)),
            "Interrupt": grpc.unary_unary_rpc_method_handler(
                self._interrupt,
                request_deserializer=pb.InterruptRequest.FromString,
                response_serializer=pb.InterruptResponse.SerializeToString),
            "ReattachExecute": grpc.unary_stream_rpc_method_handler(
                self._reattach_execute,
                request_deserializer=pb.ReattachExecuteRequest.FromString,
                response_serializer=(
                    pb.ExecutePlanResponse.SerializeToString)),
            "ReleaseExecute": grpc.unary_unary_rpc_method_handler(
                self._release_execute,
                request_deserializer=pb.ReleaseExecuteRequest.FromString,
                response_serializer=(
                    pb.ReleaseExecuteResponse.SerializeToString)),
        }
        self._server = grpc.server(
            cf.ThreadPoolExecutor(max_workers=max_workers,
                                  thread_name_prefix="daft-tpu-connect"))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")
        self._server.start()

    # ---------------------------------------------------------------- api
    @property
    def address(self) -> str:
        return f"sc://127.0.0.1:{self.port}"

    def stop(self, grace: Optional[float] = None) -> None:
        self._server.stop(grace)

    def sessions(self) -> list:
        with self._lock:
            return list(self._sessions)

    def release_session(self, session_id: str) -> bool:
        """Fleet handoff: drop this session's server-side state NOW.
        The 60s idle-TTL sweeps (the scheduler's session reaper and the
        finished-operation sweep) would reclaim it eventually; on a
        handoff the re-homed session must not leak a queue or pinned
        response buffers on the OLD replica for even that long. Running
        operations are interrupted (their scheduler handles cancel
        cooperatively), buffers are dropped with the session state, and
        the scheduler's session queue is released. True when any state
        existed."""
        with self._lock:
            st = self._sessions.pop(session_id, None)
        if st is not None:
            for op in list(st.operations.values()):
                op.request_cancel()
        released = st is not None
        try:
            from .. import serving
            sched = serving.shared_scheduler_if_running()
            if sched is not None:
                released = sched.release_session(session_id) or released
        except Exception:
            pass
        return released

    # ------------------------------------------------------------ helpers
    def _session(self, session_id: str) -> _SessionState:
        with self._lock:
            st = self._sessions.get(session_id)
            if st is None:
                st = self._sessions[session_id] = _SessionState()
            self._sweep_operations_locked(st)
            return st

    @staticmethod
    def _sweep_operations_locked(st: _SessionState) -> None:
        """Bound finished-operation retention. Finished reattachable
        operations hold their whole response buffer until the client
        RELEASEs them; a client that never does (crashed, lazy) used to
        pin every result it ever produced for the life of the session.
        Two bounds, swept opportunistically on every RPC that touches the
        session: a TTL after finish (``DAFT_TPU_SERVE_OP_TTL``) and a
        per-session retained-byte budget (``DAFT_TPU_SERVE_OP_RETAIN_BYTES``,
        newest kept first). Running operations are never swept; a swept
        operation reattaches as NOT_FOUND, same as an explicit release."""
        from ..analysis import knobs
        ttl = knobs.env_float("DAFT_TPU_SERVE_OP_TTL")
        cap = knobs.env_bytes("DAFT_TPU_SERVE_OP_RETAIN_BYTES")
        now = time.monotonic()
        finished = [(op.finished_at, oid, op)
                    for oid, op in st.operations.items()
                    if op.done.is_set() and op.finished_at is not None]
        if ttl and ttl > 0:
            for t, oid, _op in finished:
                if now - t > ttl:
                    st.operations.pop(oid, None)
        if cap and cap > 0:
            kept = 0
            still = sorted(((op.finished_at, oid, op)
                            for oid, op in st.operations.items()
                            if op.done.is_set()
                            and op.finished_at is not None),
                           key=lambda x: x[0], reverse=True)
            for _t, oid, op in still:
                kept += max(op.retained_bytes, 0)
                if kept > cap:
                    st.operations.pop(oid, None)

    def _abort(self, context, exc: Exception):
        from ..execution.cancellation import QueryCancelled
        from ..fleet.router import ReplicaUnavailable
        from ..serving import AdmissionRejected
        grpc = self._grpc
        if isinstance(exc, Unsupported):
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          f"unsupported by daft_tpu connect: {exc}")
        if isinstance(exc, ReplicaUnavailable):
            # a dead-replica routed session is a RETRYABLE condition, not
            # an internal error: structured UNAVAILABLE + retry-info (the
            # delay rides trailing metadata AND the message, so clients
            # without metadata plumbing still see it)
            try:
                context.set_trailing_metadata((
                    ("retry-delay-ms",
                     str(int(exc.retry_after_s * 1000))),))
            except Exception:
                pass
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"replica unavailable, retry in {exc.retry_after_s:.1f}s "
                f"(retry-info: retry-delay-ms="
                f"{int(exc.retry_after_s * 1000)}): {exc}")
        if isinstance(exc, AdmissionRejected):
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                          f"admission rejected ({exc.kind}): {exc}")
        if isinstance(exc, QueryCancelled):
            context.abort(grpc.StatusCode.CANCELLED, str(exc))
        context.abort(grpc.StatusCode.INTERNAL, f"{type(exc).__name__}: "
                      f"{exc}")

    # ----------------------------------------------------------- execute
    def _execute_plan(self, request: pb.ExecutePlanRequest, context
                      ) -> Iterator[pb.ExecutePlanResponse]:
        st = self._session(request.session_id)
        op_id = request.operation_id or str(uuid.uuid4())
        reattachable = any(
            o.WhichOneof("request_option") == "reattach_options"
            and o.reattach_options.reattachable
            for o in request.request_options)
        op = _Operation(op_id, request.tags, reattachable)
        with self._lock:
            st.operations[op_id] = op

        def resp() -> pb.ExecutePlanResponse:
            r = pb.ExecutePlanResponse()
            r.session_id = request.session_id
            r.server_side_session_id = st.server_side_id
            r.operation_id = op_id
            r.response_id = str(uuid.uuid4())
            return r

        aborting = False
        try:
            which = request.plan.WhichOneof("op_type")
            if which == "command":
                gen = self._execute_command(request.plan.command, st, resp)
            else:
                df = st.analyzer.plan_to_df(request.plan)
                gen = self._stream_df(df, resp, op=op,
                                      session_id=request.session_id)
            for r in gen:
                if op.cancel.is_set():
                    op.finish(error=(self._grpc.StatusCode.CANCELLED,
                                     f"operation {op_id} interrupted"))
                    aborting = True
                    context.abort(self._grpc.StatusCode.CANCELLED,
                                  f"operation {op_id} interrupted")
                op.record(r)
                yield r
            done = resp()
            done.result_complete.SetInParent()
            op.record(done)
            op.finish()
            yield done
        except Exception as exc:  # noqa: BLE001 - surfaced via grpc status
            if aborting:  # context.abort's unwind exception — re-raise
                raise
            from ..execution.cancellation import QueryCancelled
            from ..fleet.router import ReplicaUnavailable
            from ..serving import AdmissionRejected
            code = self._grpc.StatusCode.INTERNAL
            if isinstance(exc, QueryCancelled):
                code = self._grpc.StatusCode.CANCELLED
            elif isinstance(exc, AdmissionRejected):
                code = self._grpc.StatusCode.RESOURCE_EXHAUSTED
            elif isinstance(exc, ReplicaUnavailable):
                code = self._grpc.StatusCode.UNAVAILABLE
            op.finish(error=(code, f"{type(exc).__name__}: {exc}"))
            self._abort(context, exc)
        finally:
            # covers GeneratorExit (client disconnected mid-stream): a
            # reattacher must never wait on an operation whose producer is
            # gone, and a truncated buffer must not replay as a clean
            # result — record an explicit status
            if not op.done.is_set():
                op.finish(error=(
                    self._grpc.StatusCode.UNAVAILABLE,
                    f"operation {op_id}'s producer disconnected before "
                    f"completion"))
            if not reattachable:
                with self._lock:
                    st.operations.pop(op_id, None)

    # ------------------------------------------- operation-lifecycle RPCs
    def _interrupt(self, request: pb.InterruptRequest, context
                   ) -> pb.InterruptResponse:
        st = self._session(request.session_id)
        out = pb.InterruptResponse()
        out.session_id = request.session_id
        out.server_side_session_id = st.server_side_id
        T = pb.InterruptRequest.InterruptType
        with self._lock:
            ops = list(st.operations.values())
        for op in ops:
            if op.done.is_set():
                continue
            hit = (request.interrupt_type == T.INTERRUPT_TYPE_ALL
                   or (request.interrupt_type
                       == T.INTERRUPT_TYPE_OPERATION_ID
                       and op.op_id == request.operation_id)
                   or (request.interrupt_type == T.INTERRUPT_TYPE_TAG
                       and request.operation_tag in op.tags))
            if hit:
                # fires the scheduler handle's CancelToken too: the
                # running executor unwinds at its next morsel boundary
                # and releases its memory admission
                op.request_cancel()
                out.interrupted_ids.append(op.op_id)
        return out

    def _reattach_execute(self, request: pb.ReattachExecuteRequest, context
                          ) -> Iterator[pb.ExecutePlanResponse]:
        st = self._session(request.session_id)
        with self._lock:
            op = st.operations.get(request.operation_id)
        if op is None:
            context.abort(
                self._grpc.StatusCode.NOT_FOUND,
                f"operation {request.operation_id!r} not found "
                f"(never started, not reattachable, or released)")
        if not op.reattachable:
            context.abort(
                self._grpc.StatusCode.INVALID_ARGUMENT,
                f"operation {request.operation_id!r} was not started with "
                f"ReattachOptions.reattachable")
        pending, seen = op.after(request.last_response_id or None)
        yield from pending
        # still running: follow the buffer via the producer's condition
        # variable (never holding it across a yield — a slow client must
        # not block Release/Interrupt on this operation). ``seen`` is the
        # ABSOLUTE high-water mark; released prefixes shift op.base.
        while True:
            with op.cond:
                op.cond.wait_for(
                    lambda: op.done.is_set() or op.total() > seen)
                fresh = list(op.buffer[max(0, seen - op.base):])
                seen = op.total()
                finished = op.done.is_set()
            yield from fresh
            if finished and seen >= op.total():
                break
        if op.error is not None:
            context.abort(op.error[0], op.error[1])

    def _release_execute(self, request: pb.ReleaseExecuteRequest, context
                         ) -> pb.ReleaseExecuteResponse:
        st = self._session(request.session_id)
        out = pb.ReleaseExecuteResponse()
        out.session_id = request.session_id
        out.server_side_session_id = st.server_side_id
        out.operation_id = request.operation_id
        with self._lock:
            op = st.operations.get(request.operation_id)
        if op is None:
            return out  # releasing an unknown/already-released op is a no-op
        if request.WhichOneof("release") == "release_until":
            op.release_until(request.release_until.response_id)
        else:  # release_all (and unset, which clients treat the same)
            with self._lock:
                st.operations.pop(request.operation_id, None)
        return out

    def _add_artifacts(self, request_iterator, context
                       ) -> pb.AddArtifactsResponse:
        import zlib
        out = pb.AddArtifactsResponse()
        cur_name: Optional[str] = None
        cur_parts: list = []
        cur_ok = True
        cur_expect = (0, 0)  # (num_chunks, total_bytes) promised by begin
        st = None

        def finish_chunked():
            nonlocal cur_name, cur_parts, cur_ok
            if cur_name is None:
                return
            data = b"".join(cur_parts)
            # a truncated upload (client died mid-stream) must not be
            # stored as clean: the begin message promised the shape
            complete = (len(cur_parts) == cur_expect[0]
                        and len(data) == cur_expect[1])
            ok = cur_ok and complete
            if ok:  # corrupt/incomplete uploads are reported, never stored
                st.artifacts[cur_name] = data
            s = out.artifacts.add()
            s.name = cur_name
            s.is_crc_successful = ok
            cur_name, cur_parts, cur_ok = None, [], True

        for req in request_iterator:
            if st is None:
                st = self._session(req.session_id)
                out.session_id = req.session_id
                out.server_side_session_id = st.server_side_id
            which = req.WhichOneof("payload")
            if which == "batch":
                finish_chunked()
                for a in req.batch.artifacts:
                    ok = zlib.crc32(a.data.data) == a.data.crc
                    if ok:  # corrupt uploads are reported, never stored
                        st.artifacts[a.name] = a.data.data
                    s = out.artifacts.add()
                    s.name = a.name
                    s.is_crc_successful = ok
            elif which == "begin_chunk":
                finish_chunked()
                b = req.begin_chunk
                cur_name = b.name
                cur_parts = [b.initial_chunk.data]
                cur_expect = (b.num_chunks, b.total_bytes)
                cur_ok = zlib.crc32(b.initial_chunk.data) \
                    == b.initial_chunk.crc
            elif which == "chunk" and cur_name is not None:
                cur_parts.append(req.chunk.data)
                cur_ok = cur_ok and zlib.crc32(req.chunk.data) \
                    == req.chunk.crc
        if st is not None:
            finish_chunked()
        return out

    def _stream_df(self, df, resp, op: Optional[_Operation] = None,
                   session_id: str = "default"
                   ) -> Iterator[pb.ExecutePlanResponse]:
        # ExecutePlan routes through the process-shared query scheduler:
        # every Spark Connect session becomes a serving-plane session
        # (weighted fair queuing + admission control across clients), and
        # INTERRUPT cancels the RUNNING query cooperatively through the
        # handle, not just the response stream. With a fleet router
        # installed the session is consistent-hashed onto a replica
        # instead (sticky; re-routed on replica death/drain).
        from .. import fleet, serving
        router = fleet.installed_router()
        if router is not None:
            handle = router.submit(df, session=session_id)
        else:
            handle = serving.shared_scheduler().submit(
                df, session=session_id)
        if op is not None:
            op.bind_cancel(handle.cancel)
        ps = handle.result()
        table = ps.to_recordbatch().to_arrow_table()
        first = resp()
        first.schema.CopyFrom(schema_to_proto(df.schema()))
        start = 0
        emitted = False
        for chunk_start in range(0, max(table.num_rows, 1), _BATCH_ROWS):
            chunk = table.slice(chunk_start, _BATCH_ROWS)
            if chunk.num_rows == 0 and emitted:
                break
            r = first if not emitted else resp()
            sink = io.BytesIO()
            with pa.ipc.new_stream(sink, table.schema) as w:
                w.write_table(chunk)
            r.arrow_batch.row_count = chunk.num_rows
            r.arrow_batch.data = sink.getvalue()
            r.arrow_batch.start_offset = start
            start += chunk.num_rows
            emitted = True
            yield r

    def _execute_command(self, cmd: pb.Command, st: _SessionState, resp
                         ) -> Iterator[pb.ExecutePlanResponse]:
        which = cmd.WhichOneof("command_type")
        if which == "sql_command":
            # queries stay lazy: hand back a relation the client re-submits
            # (Spark's behavior for SELECTs); daft_tpu SQL is query-only so
            # every statement takes this path.
            rel = (cmd.sql_command.input if
                   cmd.sql_command.HasField("input") else
                   pb.Relation(sql=pb.SQL(query=cmd.sql_command.sql)))
            r = resp()
            r.sql_command_result.relation.CopyFrom(rel)
            yield r
            return
        if which == "create_dataframe_view":
            c = cmd.create_dataframe_view
            name = c.name
            if name in st.views and not c.replace:
                raise Unsupported(f"view {name!r} exists (replace=False)")
            st.views[name] = st.analyzer.relation_to_df(c.input)
            return
        if which == "write_operation":
            self._write(cmd.write_operation, st)
            return
        raise Unsupported(f"command {which!r}")

    def _write(self, w: pb.WriteOperation, st: _SessionState) -> None:
        import os

        df = st.analyzer.relation_to_df(w.input)
        fmt = (w.source or "parquet").lower()
        if w.WhichOneof("save_type") != "path":
            raise Unsupported("write without path (saveAsTable)")
        M = pb.WriteOperation
        exists = os.path.exists(w.path) and bool(os.listdir(w.path)) \
            if os.path.isdir(w.path) else os.path.exists(w.path)
        # Spark's default mode is errorifexists; honor it and IGNORE
        # rather than silently appending
        if w.mode in (M.SAVE_MODE_ERROR_IF_EXISTS,
                      M.SAVE_MODE_UNSPECIFIED) and exists:
            raise FileExistsError(
                f"path {w.path!r} already exists (write mode errorifexists)")
        if w.mode == M.SAVE_MODE_IGNORE and exists:
            return
        mode = ("overwrite" if w.mode == M.SAVE_MODE_OVERWRITE
                else "append")
        part_cols = list(w.partitioning_columns)
        if fmt == "parquet":
            df.write_parquet(w.path, write_mode=mode,
                             partition_cols=part_cols or None)
        elif fmt == "csv":
            df.write_csv(w.path, write_mode=mode,
                         partition_cols=part_cols or None)
        elif fmt == "json":
            df.write_json(w.path, write_mode=mode,
                          partition_cols=part_cols or None)
        else:
            raise Unsupported(f"write format {fmt!r}")

    # ----------------------------------------------------------- analyze
    def _analyze_plan(self, request: pb.AnalyzePlanRequest, context
                      ) -> pb.AnalyzePlanResponse:
        st = self._session(request.session_id)
        out = pb.AnalyzePlanResponse()
        out.session_id = request.session_id
        out.server_side_session_id = st.server_side_id
        try:
            which = request.WhichOneof("analyze")
            if which == "schema":
                df = st.analyzer.plan_to_df(request.schema.plan)
                out.schema.schema.CopyFrom(schema_to_proto(df.schema()))
            elif which == "explain":
                df = st.analyzer.plan_to_df(request.explain.plan)
                out.explain.explain_string = _explain_str(df)
            elif which == "tree_string":
                df = st.analyzer.plan_to_df(request.tree_string.plan)
                out.tree_string.tree_string = _explain_str(df)
            elif which == "spark_version":
                out.spark_version.version = _VERSION
            elif which == "ddl_parse":
                out.ddl_parse.parsed.CopyFrom(
                    parse_ddl(request.ddl_parse.ddl_string))
            else:
                raise Unsupported(f"analyze {which!r}")
        except Exception as exc:  # noqa: BLE001
            self._abort(context, exc)
        return out

    # ------------------------------------------------------------ config
    def _config(self, request: pb.ConfigRequest, context
                ) -> pb.ConfigResponse:
        st = self._session(request.session_id)
        out = pb.ConfigResponse()
        out.session_id = request.session_id
        out.server_side_session_id = st.server_side_id
        op = request.operation
        which = op.WhichOneof("op_type")
        if which == "set":
            for kv in op.set.pairs:
                st.config[kv.key] = kv.value if kv.HasField("value") else ""
        elif which == "get":
            for k in op.get.keys:
                kv = out.pairs.add()
                kv.key = k
                if k in st.config:
                    kv.value = st.config[k]
        elif which == "get_with_default":
            for d in op.get_with_default.pairs:
                kv = out.pairs.add()
                kv.key = d.key
                kv.value = st.config.get(
                    d.key, d.value if d.HasField("value") else "")
        elif which == "get_option":
            for k in op.get_option.keys:
                if k in st.config:
                    kv = out.pairs.add()
                    kv.key = k
                    kv.value = st.config[k]
        elif which == "get_all":
            prefix = (op.get_all.prefix
                      if op.get_all.HasField("prefix") else "")
            for k, v in sorted(st.config.items()):
                if k.startswith(prefix):
                    kv = out.pairs.add()
                    kv.key = k
                    kv.value = v
        elif which == "unset":
            for k in op.unset.keys:
                st.config.pop(k, None)
        elif which == "is_modifiable":
            for k in op.is_modifiable.keys:
                kv = out.pairs.add()
                kv.key = k
                kv.value = "true"
        return out


def _explain_str(df) -> str:
    import contextlib
    import io as _io
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        df.explain(show_all=True)
    return buf.getvalue()


def start_server(port: int = 0) -> SparkConnectServer:
    return SparkConnectServer(port)
