"""Embedded Spark Connect gRPC server.

Reference: ``src/daft-connect/src/connect_service.rs:235-334`` — a tonic
``SparkConnectService`` whose ``execute_plan`` / ``analyze_plan`` / ``config``
translate Spark protos through the engine and stream Arrow batches back. Here
the service is built on grpc's generic method handlers against the
wire-compatible subset protos (``spark_connect_subset.proto``), so a Spark
Connect client can point at ``sc://host:port`` and run queries on daft_tpu.

Usage::

    from daft_tpu.connect import start_server
    server = start_server()           # SparkConnectServer, .port/.address
    ...
    server.stop()
"""

from __future__ import annotations

import io
import threading
import uuid
from typing import Dict, Iterator, Optional

import pyarrow as pa

from . import spark_connect_subset_pb2 as pb
from .analyzer import (SparkAnalyzer, Unsupported, dtype_to_proto, parse_ddl,
                       schema_to_proto)

_SERVICE = "spark.connect.SparkConnectService"
_VERSION = "3.5.1+daft-tpu"

# rows per streamed ArrowBatch message (Spark chunks large results the same
# way; grpc messages default-cap at 4MB)
_BATCH_ROWS = 1 << 16


class _SessionState:
    def __init__(self):
        self.config: Dict[str, str] = {}
        self.views: Dict[str, object] = {}
        self.server_side_id = uuid.uuid4().hex

    @property
    def analyzer(self) -> SparkAnalyzer:
        return SparkAnalyzer(self.views)


class SparkConnectServer:
    """grpc server exposing daft_tpu as a Spark Connect endpoint."""

    def __init__(self, port: int = 0, max_workers: int = 8):
        import concurrent.futures as cf

        import grpc

        self._grpc = grpc
        self._sessions: Dict[str, _SessionState] = {}
        self._lock = threading.Lock()

        handlers = {
            "ExecutePlan": grpc.unary_stream_rpc_method_handler(
                self._execute_plan,
                request_deserializer=pb.ExecutePlanRequest.FromString,
                response_serializer=pb.ExecutePlanResponse.SerializeToString),
            "AnalyzePlan": grpc.unary_unary_rpc_method_handler(
                self._analyze_plan,
                request_deserializer=pb.AnalyzePlanRequest.FromString,
                response_serializer=pb.AnalyzePlanResponse.SerializeToString),
            "Config": grpc.unary_unary_rpc_method_handler(
                self._config,
                request_deserializer=pb.ConfigRequest.FromString,
                response_serializer=pb.ConfigResponse.SerializeToString),
        }
        self._server = grpc.server(
            cf.ThreadPoolExecutor(max_workers=max_workers,
                                  thread_name_prefix="daft-tpu-connect"))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")
        self._server.start()

    # ---------------------------------------------------------------- api
    @property
    def address(self) -> str:
        return f"sc://127.0.0.1:{self.port}"

    def stop(self, grace: Optional[float] = None) -> None:
        self._server.stop(grace)

    # ------------------------------------------------------------ helpers
    def _session(self, session_id: str) -> _SessionState:
        with self._lock:
            st = self._sessions.get(session_id)
            if st is None:
                st = self._sessions[session_id] = _SessionState()
            return st

    def _abort(self, context, exc: Exception):
        grpc = self._grpc
        if isinstance(exc, Unsupported):
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          f"unsupported by daft_tpu connect: {exc}")
        context.abort(grpc.StatusCode.INTERNAL, f"{type(exc).__name__}: "
                      f"{exc}")

    # ----------------------------------------------------------- execute
    def _execute_plan(self, request: pb.ExecutePlanRequest, context
                      ) -> Iterator[pb.ExecutePlanResponse]:
        st = self._session(request.session_id)
        op_id = request.operation_id or str(uuid.uuid4())

        def resp() -> pb.ExecutePlanResponse:
            r = pb.ExecutePlanResponse()
            r.session_id = request.session_id
            r.server_side_session_id = st.server_side_id
            r.operation_id = op_id
            r.response_id = str(uuid.uuid4())
            return r

        try:
            which = request.plan.WhichOneof("op_type")
            if which == "command":
                yield from self._execute_command(request.plan.command, st,
                                                 resp)
            else:
                df = st.analyzer.plan_to_df(request.plan)
                yield from self._stream_df(df, resp)
        except Exception as exc:  # noqa: BLE001 - surfaced via grpc status
            self._abort(context, exc)
            return
        done = resp()
        done.result_complete.SetInParent()
        yield done

    def _stream_df(self, df, resp) -> Iterator[pb.ExecutePlanResponse]:
        table = df.to_arrow()
        first = resp()
        first.schema.CopyFrom(schema_to_proto(df.schema()))
        start = 0
        emitted = False
        for chunk_start in range(0, max(table.num_rows, 1), _BATCH_ROWS):
            chunk = table.slice(chunk_start, _BATCH_ROWS)
            if chunk.num_rows == 0 and emitted:
                break
            r = first if not emitted else resp()
            sink = io.BytesIO()
            with pa.ipc.new_stream(sink, table.schema) as w:
                w.write_table(chunk)
            r.arrow_batch.row_count = chunk.num_rows
            r.arrow_batch.data = sink.getvalue()
            r.arrow_batch.start_offset = start
            start += chunk.num_rows
            emitted = True
            yield r

    def _execute_command(self, cmd: pb.Command, st: _SessionState, resp
                         ) -> Iterator[pb.ExecutePlanResponse]:
        which = cmd.WhichOneof("command_type")
        if which == "sql_command":
            # queries stay lazy: hand back a relation the client re-submits
            # (Spark's behavior for SELECTs); daft_tpu SQL is query-only so
            # every statement takes this path.
            rel = (cmd.sql_command.input if
                   cmd.sql_command.HasField("input") else
                   pb.Relation(sql=pb.SQL(query=cmd.sql_command.sql)))
            r = resp()
            r.sql_command_result.relation.CopyFrom(rel)
            yield r
            return
        if which == "create_dataframe_view":
            c = cmd.create_dataframe_view
            name = c.name
            if name in st.views and not c.replace:
                raise Unsupported(f"view {name!r} exists (replace=False)")
            st.views[name] = st.analyzer.relation_to_df(c.input)
            return
        if which == "write_operation":
            self._write(cmd.write_operation, st)
            return
        raise Unsupported(f"command {which!r}")

    def _write(self, w: pb.WriteOperation, st: _SessionState) -> None:
        import os

        df = st.analyzer.relation_to_df(w.input)
        fmt = (w.source or "parquet").lower()
        if w.WhichOneof("save_type") != "path":
            raise Unsupported("write without path (saveAsTable)")
        M = pb.WriteOperation
        exists = os.path.exists(w.path) and bool(os.listdir(w.path)) \
            if os.path.isdir(w.path) else os.path.exists(w.path)
        # Spark's default mode is errorifexists; honor it and IGNORE
        # rather than silently appending
        if w.mode in (M.SAVE_MODE_ERROR_IF_EXISTS,
                      M.SAVE_MODE_UNSPECIFIED) and exists:
            raise FileExistsError(
                f"path {w.path!r} already exists (write mode errorifexists)")
        if w.mode == M.SAVE_MODE_IGNORE and exists:
            return
        mode = ("overwrite" if w.mode == M.SAVE_MODE_OVERWRITE
                else "append")
        part_cols = list(w.partitioning_columns)
        if fmt == "parquet":
            df.write_parquet(w.path, write_mode=mode,
                             partition_cols=part_cols or None)
        elif fmt == "csv":
            df.write_csv(w.path, write_mode=mode,
                         partition_cols=part_cols or None)
        elif fmt == "json":
            df.write_json(w.path, write_mode=mode,
                          partition_cols=part_cols or None)
        else:
            raise Unsupported(f"write format {fmt!r}")

    # ----------------------------------------------------------- analyze
    def _analyze_plan(self, request: pb.AnalyzePlanRequest, context
                      ) -> pb.AnalyzePlanResponse:
        st = self._session(request.session_id)
        out = pb.AnalyzePlanResponse()
        out.session_id = request.session_id
        out.server_side_session_id = st.server_side_id
        try:
            which = request.WhichOneof("analyze")
            if which == "schema":
                df = st.analyzer.plan_to_df(request.schema.plan)
                out.schema.schema.CopyFrom(schema_to_proto(df.schema()))
            elif which == "explain":
                df = st.analyzer.plan_to_df(request.explain.plan)
                out.explain.explain_string = _explain_str(df)
            elif which == "tree_string":
                df = st.analyzer.plan_to_df(request.tree_string.plan)
                out.tree_string.tree_string = _explain_str(df)
            elif which == "spark_version":
                out.spark_version.version = _VERSION
            elif which == "ddl_parse":
                out.ddl_parse.parsed.CopyFrom(
                    parse_ddl(request.ddl_parse.ddl_string))
            else:
                raise Unsupported(f"analyze {which!r}")
        except Exception as exc:  # noqa: BLE001
            self._abort(context, exc)
        return out

    # ------------------------------------------------------------ config
    def _config(self, request: pb.ConfigRequest, context
                ) -> pb.ConfigResponse:
        st = self._session(request.session_id)
        out = pb.ConfigResponse()
        out.session_id = request.session_id
        out.server_side_session_id = st.server_side_id
        op = request.operation
        which = op.WhichOneof("op_type")
        if which == "set":
            for kv in op.set.pairs:
                st.config[kv.key] = kv.value if kv.HasField("value") else ""
        elif which == "get":
            for k in op.get.keys:
                kv = out.pairs.add()
                kv.key = k
                if k in st.config:
                    kv.value = st.config[k]
        elif which == "get_with_default":
            for d in op.get_with_default.pairs:
                kv = out.pairs.add()
                kv.key = d.key
                kv.value = st.config.get(
                    d.key, d.value if d.HasField("value") else "")
        elif which == "get_option":
            for k in op.get_option.keys:
                if k in st.config:
                    kv = out.pairs.add()
                    kv.key = k
                    kv.value = st.config[k]
        elif which == "get_all":
            prefix = (op.get_all.prefix
                      if op.get_all.HasField("prefix") else "")
            for k, v in sorted(st.config.items()):
                if k.startswith(prefix):
                    kv = out.pairs.add()
                    kv.key = k
                    kv.value = v
        elif which == "unset":
            for k in op.unset.keys:
                st.config.pop(k, None)
        elif which == "is_modifiable":
            for k in op.is_modifiable.keys:
                kv = out.pairs.add()
                kv.key = k
                kv.value = "true"
        return out


def _explain_str(df) -> str:
    import contextlib
    import io as _io
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        df.explain(show_all=True)
    return buf.getvalue()


def start_server(port: int = 0) -> SparkConnectServer:
    return SparkConnectServer(port)
