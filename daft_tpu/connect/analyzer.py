"""Spark Connect relation/expression → daft_tpu translation.

Reference: the embedded Spark Connect server's analyzer
(``src/daft-connect/src/spark_analyzer/mod.rs`` translates Spark relation
protos into the engine's LogicalPlan; function-name mapping in
``src/daft-connect/src/functions/``). Here the target is the daft_tpu
DataFrame/Expression API directly — every supported ``Relation`` variant maps
onto the equivalent DataFrame verb and unresolved Spark function names map
onto Expression methods. Unsupported variants raise ``Unsupported`` which the
server surfaces as grpc UNIMPLEMENTED.
"""

from __future__ import annotations

import datetime
import io
from typing import Dict, List, Optional

import pyarrow as pa

from . import spark_connect_subset_pb2 as pb


class Unsupported(Exception):
    """Relation / expression / function outside the implemented subset."""


def _require(cond: bool, what: str):
    if not cond:
        raise Unsupported(what)


class SparkAnalyzer:
    """Translates one session's plans. ``views`` maps temp-view names to
    daft_tpu DataFrames (populated by CreateDataFrameViewCommand)."""

    def __init__(self, views: Optional[Dict[str, object]] = None):
        self.views = views if views is not None else {}

    # ------------------------------------------------------------- plans
    def plan_to_df(self, plan: pb.Plan):
        _require(plan.WhichOneof("op_type") == "root",
                 "only Plan.root is executable as a query")
        return self.relation_to_df(plan.root)

    def relation_to_df(self, rel: pb.Relation):
        kind = rel.WhichOneof("rel_type")
        _require(kind is not None,
                 "relation outside the supported subset (unknown rel_type)")
        fn = getattr(self, f"_rel_{kind}", None)
        _require(fn is not None, f"relation type {kind!r}")
        return fn(getattr(rel, kind))

    # ----------------------------------------------------- relation impls
    def _rel_range(self, r: pb.Range):
        import daft_tpu as dt
        start = r.start if r.HasField("start") else 0
        step = r.step or 1
        nparts = r.num_partitions if r.HasField("num_partitions") else 1
        return dt.range(start, r.end, step, partitions=max(nparts, 1))

    def _rel_sql(self, r: pb.SQL):
        import daft_tpu as dt
        from ..sql.sql import SQLCatalog
        if self.views:
            return dt.sql(r.query, catalog=SQLCatalog(dict(self.views)))
        return dt.sql(r.query)

    def _rel_read(self, r: pb.Read):
        import daft_tpu as dt
        which = r.WhichOneof("read_type")
        if which == "named_table":
            name = r.named_table.unparsed_identifier
            if name in self.views:
                return self.views[name]
            from .. import session as sess
            return sess.read_table(name)
        _require(which == "data_source", "read without source")
        ds = r.data_source
        fmt = (ds.format or "parquet").lower()
        paths = list(ds.paths)
        _require(bool(paths), "read.data_source without paths")
        readers = {"parquet": dt.read_parquet, "csv": dt.read_csv,
                   "json": dt.read_json}
        _require(fmt in readers, f"read format {fmt!r}")
        return readers[fmt](paths if len(paths) > 1 else paths[0])

    def _rel_local_relation(self, r: pb.LocalRelation):
        import daft_tpu as dt
        if not r.HasField("data"):
            # schema-only: an empty frame with the declared columns
            _require(r.HasField("schema"),
                     "LocalRelation without data or schema")
            proto = parse_ddl(r.schema)
            _require(proto.WhichOneof("kind") == "struct",
                     "LocalRelation schema must be a struct DDL")
            cols = {f.name: pa.array([], type=proto_to_dtype(
                f.data_type).to_arrow()) for f in proto.struct.fields}
            return dt.from_arrow(pa.table(cols))
        with pa.ipc.open_stream(pa.BufferReader(r.data)) as rd:
            table = rd.read_all()
        return dt.from_arrow(table)

    def _rel_to_schema(self, r: pb.ToSchema):
        """Cast to the declared struct schema, column by name (pyspark's
        createDataFrame-with-schema path)."""
        from daft_tpu import col
        df = self.relation_to_df(r.input)
        _require(r.schema.WhichOneof("kind") == "struct",
                 "to_schema needs a struct DataType")
        exprs = []
        for f in r.schema.struct.fields:
            _require(f.name in df.column_names,
                     f"to_schema: column {f.name!r} missing")
            exprs.append(col(f.name).cast(
                proto_to_dtype(f.data_type)).alias(f.name))
        return df.select(*exprs)

    def _rel_html_string(self, r: pb.HtmlString):
        """Spark's _repr_html_ path: one row, one column of rendered HTML.
        Cell values and headers are escaped — data must never inject
        markup."""
        import html as _html

        import daft_tpu as dt
        rows, names, truncated = self._fetch_rows(r.input, r.num_rows)
        out = ["<table border='1'>", "<tr>"]
        out += [f"<th>{_html.escape(n)}</th>" for n in names]
        out.append("</tr>")
        for row in rows:
            out.append("<tr>" + "".join(
                f"<td>{_html.escape(_fmt_cell(row[c], r.truncate))}</td>"
                for c in names) + "</tr>")
        out.append("</table>")
        if truncated:
            out.append(f"only showing top {r.num_rows} rows")
        return dt.from_pydict({"html_string": ["\n".join(out) + "\n"]})

    def _fetch_rows(self, input_rel: pb.Relation, num_rows: int):
        """Shared show/html prologue: first num_rows(+1 to detect
        truncation) rows as dicts plus column names."""
        df = self.relation_to_df(input_rel).limit(num_rows + 1)
        rows = df.to_pylist()
        truncated = len(rows) > num_rows
        return rows[:num_rows], df.column_names, truncated

    def _rel_project(self, r: pb.Project):
        df = self.relation_to_df(r.input)
        cols = []
        for e in r.expressions:
            if e.WhichOneof("expr_type") == "unresolved_star":
                cols.extend(df.columns)
            else:
                cols.append(self.expr(e))
        return df.select(*cols)

    def _rel_filter(self, r: pb.Filter):
        return self.relation_to_df(r.input).where(self.expr(r.condition))

    def _rel_limit(self, r: pb.Limit):
        return self.relation_to_df(r.input).limit(r.limit)

    def _rel_offset(self, r: pb.Offset):
        return self.relation_to_df(r.input).offset(r.offset)

    def _rel_tail(self, r: pb.Tail):
        df = self.relation_to_df(r.input)
        n = df.count_rows()
        return df.limit(r.limit, offset=max(n - r.limit, 0))

    def _rel_sort(self, r: pb.Sort):
        df = self.relation_to_df(r.input)
        by, desc = [], []
        for o in r.order:
            by.append(self.expr(o.child))
            desc.append(o.direction ==
                        pb.Expression.SortOrder.SORT_DIRECTION_DESCENDING)
        return df.sort(by, desc=desc)

    def _rel_aggregate(self, r: pb.Aggregate):
        df = self.relation_to_df(r.input)
        _require(r.group_type in (
            pb.Aggregate.GROUP_TYPE_GROUPBY,
            pb.Aggregate.GROUP_TYPE_UNSPECIFIED),
            "only GROUPBY aggregation (no rollup/cube/pivot)")
        aggs = [self.expr(e) for e in r.aggregate_expressions]
        if r.grouping_expressions:
            keys = [self.expr(e) for e in r.grouping_expressions]
            return df.groupby(*keys).agg(*aggs)
        return df.agg(*aggs)

    def _rel_join(self, r: pb.Join):
        left = self.relation_to_df(r.left)
        right = self.relation_to_df(r.right)
        J = pb.Join.JoinType
        how = {J.JOIN_TYPE_INNER: "inner", J.JOIN_TYPE_FULL_OUTER: "outer",
               J.JOIN_TYPE_LEFT_OUTER: "left", J.JOIN_TYPE_RIGHT_OUTER:
               "right", J.JOIN_TYPE_LEFT_ANTI: "anti",
               J.JOIN_TYPE_LEFT_SEMI: "semi", J.JOIN_TYPE_CROSS: "cross",
               J.JOIN_TYPE_UNSPECIFIED: "inner"}.get(r.join_type)
        _require(how is not None, f"join type {r.join_type}")
        if how == "cross":
            return left.join(right, how="cross")
        if r.using_columns:
            on = list(r.using_columns)
            return left.join(right, on=on, how=how)
        _require(r.HasField("join_condition"),
                 "join without using_columns or condition")
        lk, rk = self._equi_keys(r.join_condition)
        return left.join(right, left_on=lk, right_on=rk, how=how)

    def _equi_keys(self, cond: pb.Expression):
        """Decompose `a == b [AND c == d ...]` into left/right key lists."""
        lk: List = []
        rk: List = []

        def walk(e: pb.Expression):
            _require(e.WhichOneof("expr_type") == "unresolved_function",
                     "non-equi join condition")
            f = e.unresolved_function
            if f.function_name in ("and", "AND"):
                for a in f.arguments:
                    walk(a)
                return
            _require(f.function_name not in ("eqNullSafe", "<=>"),
                     "null-safe equality (<=>) join keys: NULL <=> NULL "
                     "must match, which hash join keys do not honor")
            _require(f.function_name in ("==", "="),
                     f"join condition operator {f.function_name!r}")
            _require(len(f.arguments) == 2, "binary equality expected")
            lk.append(self.expr(f.arguments[0]))
            rk.append(self.expr(f.arguments[1]))

        walk(cond)
        return lk, rk

    def _rel_set_op(self, r: pb.SetOperation):
        left = self.relation_to_df(r.left_input)
        right = self.relation_to_df(r.right_input)
        T = pb.SetOperation.SetOpType
        is_all = r.is_all if r.HasField("is_all") else False
        if r.set_op_type == T.SET_OP_TYPE_UNION:
            return left.union_all(right) if is_all else left.union(right)
        if r.set_op_type == T.SET_OP_TYPE_INTERSECT:
            return (left.intersect_all(right) if is_all
                    else left.intersect(right))
        if r.set_op_type == T.SET_OP_TYPE_EXCEPT:
            return (left.except_all(right) if is_all
                    else left.except_distinct(right))
        raise Unsupported(f"set op {r.set_op_type}")

    def _rel_deduplicate(self, r: pb.Deduplicate):
        df = self.relation_to_df(r.input)
        if r.column_names:
            return df.distinct(*r.column_names)
        return df.distinct()

    def _rel_sample(self, r: pb.Sample):
        df = self.relation_to_df(r.input)
        frac = r.upper_bound - r.lower_bound
        seed = r.seed if r.HasField("seed") else None
        with_rep = (r.with_replacement if r.HasField("with_replacement")
                    else False)
        return df.sample(fraction=frac, with_replacement=with_rep, seed=seed)

    def _rel_repartition(self, r: pb.Repartition):
        df = self.relation_to_df(r.input)
        shuffle = r.shuffle if r.HasField("shuffle") else False
        if shuffle:
            return df.repartition(r.num_partitions)
        return df.into_partitions(r.num_partitions)

    def _rel_subquery_alias(self, r: pb.SubqueryAlias):
        return self.relation_to_df(r.input)

    def _rel_to_df(self, r: pb.ToDF):
        df = self.relation_to_df(r.input)
        old = df.column_names
        _require(len(old) == len(r.column_names),
                 f"toDF with {len(r.column_names)} names on "
                 f"{len(old)} columns")
        return df.with_columns_renamed(dict(zip(old, r.column_names)))

    def _rel_with_columns_renamed(self, r: pb.WithColumnsRenamed):
        df = self.relation_to_df(r.input)
        mapping = {rn.col_name: rn.new_col_name for rn in r.renames}
        return df.with_columns_renamed(mapping)

    def _rel_with_columns(self, r: pb.WithColumns):
        df = self.relation_to_df(r.input)
        cols = {}
        for a in r.aliases:
            _require(len(a.name) == 1, "multi-name alias in withColumns")
            cols[a.name[0]] = self.expr(a.expr)
        # one simultaneous with_columns: every expression binds against the
        # INPUT schema (Spark's withColumns semantics), not left-to-right
        return df.with_columns(cols)

    def _rel_drop(self, r: pb.Drop):
        df = self.relation_to_df(r.input)
        names = list(r.column_names)
        for e in r.columns:
            _require(e.WhichOneof("expr_type") == "unresolved_attribute",
                     "drop with non-column expression")
            names.append(e.unresolved_attribute.unparsed_identifier)
        return df.exclude(*names)

    def _rel_show_string(self, r: pb.ShowString):
        """Renders like Spark's show(): a one-row, one-column table holding
        the formatted text."""
        import daft_tpu as dt
        rows, names, truncated = self._fetch_rows(r.input, r.num_rows)
        cells = [[_fmt_cell(row[c], r.truncate) for c in names]
                 for row in rows]
        widths = [max([len(n)] + [len(c[i]) for c in cells])
                  for i, n in enumerate(names)]
        sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        out = [sep,
               "|" + "|".join(f" {n:<{w}} " for n, w in zip(names, widths))
               + "|", sep]
        for c in cells:
            out.append("|" + "|".join(
                f" {v:<{w}} " for v, w in zip(c, widths)) + "|")
        out.append(sep)
        if truncated:
            out.append(f"only showing top {r.num_rows} rows")
        return dt.from_pydict({"show_string": ["\n".join(out) + "\n"]})

    # ------------------------------------------------------- expressions
    def expr(self, e: pb.Expression):
        kind = e.WhichOneof("expr_type")
        _require(kind is not None, "expression outside supported subset")
        fn = getattr(self, f"_expr_{kind}", None)
        _require(fn is not None, f"expression type {kind!r}")
        return fn(getattr(e, kind))

    def _expr_literal(self, lit: pb.Expression.Literal):
        from daft_tpu import lit as L
        which = lit.WhichOneof("literal_type")
        _require(which is not None, "empty literal")
        if which == "null":
            return L(None)
        v = getattr(lit, which)
        if which == "date":
            v = datetime.date(1970, 1, 1) + datetime.timedelta(days=v)
        elif which in ("timestamp", "timestamp_ntz"):
            v = (datetime.datetime(1970, 1, 1)
                 + datetime.timedelta(microseconds=v))
        return L(v)

    def _expr_unresolved_attribute(self,
                                   a: pb.Expression.UnresolvedAttribute):
        from daft_tpu import col
        return col(a.unparsed_identifier)

    def _expr_alias(self, a: pb.Expression.Alias):
        _require(len(a.name) == 1, "multi-name alias")
        return self.expr(a.expr).alias(a.name[0])

    def _expr_cast(self, c: pb.Expression.Cast):
        inner = self.expr(c.expr)
        which = c.WhichOneof("cast_to_type")
        if which == "type_str":
            dtype = _parse_spark_type_str(c.type_str)
        else:
            dtype = proto_to_dtype(c.type)
        return inner.cast(dtype)

    def _expr_expression_string(self, s: pb.Expression.ExpressionString):
        from daft_tpu import sql_expr
        return sql_expr(s.expression)

    def _expr_sort_order(self, o: pb.Expression.SortOrder):
        # bare sort order outside Sort: evaluate the child
        return self.expr(o.child)

    def _expr_unresolved_function(self,
                                  f: pb.Expression.UnresolvedFunction):
        name = f.function_name
        # count(*) / count(1) → count rows; must short-circuit BEFORE
        # translating arguments (a bare star has no expression form)
        if name == "count" and (not f.arguments or _is_star_or_one(
                f.arguments[0])):
            return _count_all()
        args = [self.expr(a) for a in f.arguments]
        if f.is_distinct:
            _require(name in ("count",), f"DISTINCT {name}")
            return args[0].count_distinct()
        fn = _FUNCTIONS.get(name)
        _require(fn is not None, f"function {name!r}")
        return fn(*args)


def _fmt_cell(v, truncate: int) -> str:
    """Spark's show()/htmlString cell rendering: NULL text + truncation."""
    s = "NULL" if v is None else str(v)
    if truncate <= 0 or len(s) <= truncate:
        return s
    return s[:max(truncate - 3, 1)] + "..."


def _count_all():
    from daft_tpu import lit
    return lit(1).count("all").alias("count")


def _is_star_or_one(e: pb.Expression) -> bool:
    k = e.WhichOneof("expr_type")
    if k == "unresolved_star":
        return True
    if k == "literal":
        lt = e.literal.WhichOneof("literal_type")
        return lt in ("integer", "long") and getattr(e.literal, lt) == 1
    return False


def _null_safe_eq(a, b):
    """Spark `<=>`: never NULL — and_kleene(NULL, False)=False makes each
    disjunct definite before the OR."""
    return (a.is_null() & b.is_null()) \
        | ((a == b) & a.not_null() & b.not_null())


# Spark unresolved function name → daft_tpu Expression builder. pyspark's
# Column operators arrive as the operator symbol; pyspark.sql.functions
# arrive by name.
_FUNCTIONS = {
    "+": lambda a, b: a + b,
    "-": lambda a, b=None: (-a) if b is None else a - b,
    "negative": lambda a: -a,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b,
    "%": lambda a, b: a % b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "=": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<=>": lambda a, b: _null_safe_eq(a, b),
    "eqNullSafe": lambda a, b: _null_safe_eq(a, b),
    "and": lambda a, b: a & b,
    "or": lambda a, b: a | b,
    "not": lambda a: ~a,
    "!": lambda a: ~a,
    "isnull": lambda a: a.is_null(),
    "isnotnull": lambda a: a.not_null(),
    "in": lambda a, *vs: a.is_in(list(vs)),
    "between": lambda a, lo, hi: a.between(lo, hi),
    "abs": lambda a: abs(a),
    "sum": lambda a: a.sum(),
    "avg": lambda a: a.mean(),
    "mean": lambda a: a.mean(),
    "min": lambda a: a.min(),
    "max": lambda a: a.max(),
    "count": lambda a: a.count(),
    "stddev": lambda a: a.stddev(),
    "stddev_samp": lambda a: a.stddev(),
    "first": lambda a: a.any_value(),
    "any_value": lambda a: a.any_value(),
    "collect_list": lambda a: a.agg_list(),
    "coalesce": lambda *a: __import__("daft_tpu").coalesce(*a),
    "upper": lambda a: a.str.upper(),
    "lower": lambda a: a.str.lower(),
    "length": lambda a: a.str.length(),
    "contains": lambda a, b: a.str.contains(b),
    "startswith": lambda a, b: a.str.startswith(b),
    "endswith": lambda a, b: a.str.endswith(b),
    "concat": lambda *a: _concat(*a),
    "substr": lambda a, start, length=None: _substr(a, start, length),
    "substring": lambda a, start, length=None: _substr(a, start, length),
    "like": lambda a, p: a.str.match(_like_to_regex(p)),
    "rlike": lambda a, p: a.str.match(_expr_literal_str(p)),
    "year": lambda a: a.dt.year(),
    "month": lambda a: a.dt.month(),
    "dayofmonth": lambda a: a.dt.day(),
    "hour": lambda a: a.dt.hour(),
    "minute": lambda a: a.dt.minute(),
    "second": lambda a: a.dt.second(),
    "sqrt": lambda a: a ** 0.5,
    "power": lambda a, b: a ** b,
    "pow": lambda a, b: a ** b,
    "floor": lambda a: a.floor(),
    "ceil": lambda a: a.ceil(),
    "round": lambda a, n=None: a.round(n) if n is not None else a.round(),
}


def _concat(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


def _expr_literal_str(e) -> str:
    """Extract a python string from a lit() expression argument."""
    _require(getattr(e, "op", None) == "lit" and
             isinstance(e.params[0], str), "string literal expected")
    return e.params[0]


def _like_to_regex(p) -> str:
    import re
    pat = _expr_literal_str(p)
    return "^" + re.escape(pat).replace("%", ".*").replace("_", ".") + "$"


def _substr(a, start, length):
    # Spark substr is 1-based
    s = start - 1
    if length is None:
        return a.str.substr(s)
    return a.str.substr(s, length)


# ---------------------------------------------------------------- types

def dtype_to_proto(dtype) -> pb.DataType:
    """daft_tpu DataType → Spark Connect DataType proto."""
    from ..datatype import DataType as DT
    k = dtype.kind
    simple = {
        "null": "null", "bool": "boolean", "int8": "byte", "int16": "short",
        "int32": "integer", "int64": "long", "uint8": "short",
        "uint16": "integer", "uint32": "long", "uint64": "long",
        "float32": "float", "float64": "double", "string": "string",
        "binary": "binary", "fixed_size_binary": "binary", "date": "date",
        "timestamp": "timestamp",
    }
    out = pb.DataType()
    if k in simple:
        getattr(out, simple[k]).SetInParent()
        return out
    if k == "decimal128":
        out.decimal.precision = dtype.precision
        out.decimal.scale = dtype.scale
        return out
    if k in ("list", "fixed_size_list", "embedding"):
        out.array.element_type.CopyFrom(dtype_to_proto(dtype.inner))
        out.array.contains_null = True
        return out
    if k == "struct":
        for name, ft in dtype.fields.items():
            f = out.struct.fields.add()
            f.name = name
            f.data_type.CopyFrom(dtype_to_proto(ft))
            f.nullable = True
        return out
    if k == "map":
        out.map.key_type.CopyFrom(dtype_to_proto(dtype.key_type))
        out.map.value_type.CopyFrom(dtype_to_proto(dtype.value_type))
        out.map.value_contains_null = True
        return out
    out.unparsed.data_type_string = str(dtype)
    return out


def proto_to_dtype(t: pb.DataType):
    """Spark Connect DataType proto → daft_tpu DataType."""
    from ..datatype import DataType as DT
    kind = t.WhichOneof("kind")
    _require(kind is not None, "empty DataType")
    simple = {
        "null": DT.null, "boolean": DT.bool, "byte": DT.int8,
        "short": DT.int16, "integer": DT.int32, "long": DT.int64,
        "float": DT.float32, "double": DT.float64, "string": DT.string,
        "binary": DT.binary, "date": DT.date, "timestamp": DT.timestamp,
        "timestamp_ntz": DT.timestamp,
    }
    if kind in simple:
        return simple[kind]()
    if kind == "decimal":
        d = t.decimal
        return DT.decimal128(d.precision if d.HasField("precision") else 10,
                             d.scale if d.HasField("scale") else 0)
    if kind == "array":
        return DT.list(proto_to_dtype(t.array.element_type))
    if kind == "struct":
        return DT.struct({f.name: proto_to_dtype(f.data_type)
                          for f in t.struct.fields})
    if kind == "map":
        return DT.map(proto_to_dtype(t.map.key_type),
                      proto_to_dtype(t.map.value_type))
    if kind == "unparsed":
        return _parse_spark_type_str(t.unparsed.data_type_string)
    raise Unsupported(f"DataType {kind!r}")


_TYPE_STRS = {
    "boolean": "bool", "bool": "bool", "tinyint": "int8", "byte": "int8",
    "smallint": "int16", "short": "int16", "int": "int32",
    "integer": "int32", "bigint": "int64", "long": "int64",
    "float": "float32", "real": "float32", "double": "float64",
    "string": "string", "varchar": "string", "binary": "binary",
    "date": "date", "timestamp": "timestamp", "void": "null",
}


def _parse_spark_type_str(s: str):
    from ..datatype import DataType as DT
    base = s.strip().lower()
    if base.startswith("array<") and base.endswith(">"):
        return DT.list(_parse_spark_type_str(base[6:-1]))
    if base.startswith("decimal"):
        import re
        m = re.match(r"decimal\((\d+),\s*(\d+)\)", base)
        if m:
            return DT.decimal128(int(m.group(1)), int(m.group(2)))
        return DT.decimal128(10, 0)
    name = _TYPE_STRS.get(base.split("(")[0])
    _require(name is not None, f"type string {s!r}")
    return getattr(DT, name)()


def parse_ddl(ddl: str) -> pb.DataType:
    """`a INT, b STRING` (or a single type string) → DataType proto."""
    ddl = ddl.strip()
    try:  # a bare type string first — "decimal(10,2)" contains a comma
        return dtype_to_proto(_parse_spark_type_str(ddl))
    except Unsupported:
        pass
    out = pb.DataType()
    for part in _split_top_level(ddl):
        toks = part.strip().split(None, 1)
        _require(len(toks) == 2, f"DDL field {part!r}")
        f = out.struct.fields.add()
        f.name = toks[0].strip("`")
        f.data_type.CopyFrom(dtype_to_proto(_parse_spark_type_str(toks[1])))
        f.nullable = True
    return out


def _split_top_level(s: str) -> List[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "<(":
            depth += 1
        elif ch in ">)":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return parts


def schema_to_proto(schema) -> pb.DataType:
    """daft_tpu Schema → Spark struct DataType."""
    out = pb.DataType()
    for f in schema:
        sf = out.struct.fields.add()
        sf.name = f.name
        sf.data_type.CopyFrom(dtype_to_proto(f.dtype))
        sf.nullable = True
    return out
