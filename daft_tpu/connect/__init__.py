"""Spark Connect frontend: daft_tpu as a Spark Connect endpoint.

Reference capability: ``src/daft-connect`` (tonic gRPC SparkConnectService
translating Spark relation protos into the engine's plans) + the
``daft/pyspark`` SparkSession shim. This package re-creates that surface on
grpc + a hand-written wire-compatible protocol subset
(``spark_connect_subset.proto``)."""

from .server import SparkConnectServer, start_server

__all__ = ["SparkConnectServer", "start_server"]
