"""Vendored minimal Spark Connect CLIENT, pyspark-flavored.

pyspark is not installable in this environment (VERDICT r2 item 10), so
wire-compatibility is validated by this vendored client instead: it
mirrors the pyspark Spark Connect client's REQUEST PATTERNS — a
``SparkSession``-style entry point, ``UserContext`` + ``client_type`` on
every request, ``AnalyzePlan(schema)`` before a ``.schema`` access,
streaming ``ExecutePlan`` with Arrow-IPC batch decode, Column-expression
building via ``UnresolvedFunction``/``UnresolvedAttribute`` (exactly the
proto shapes ``pyspark.sql.connect.expressions`` emits) — against the
server's proto subset.

Users without pyspark can also use it directly::

    from daft_tpu.connect.client import connect
    spark = connect("127.0.0.1:15002")
    spark.sql("SELECT 1 AS x").collect()

Known incompatibilities with a full pyspark client (the proto SUBSET —
``spark_connect_subset.proto`` — omits them): reattachable execution /
ReleaseExecute, artifact transfer (UDF pickles), interrupt, streaming
queries, and the full literal/datatype matrix. Everything the analyzer
supports (25 relation ops) is reachable through this client.
"""

from __future__ import annotations

import io
import uuid
from typing import Any, Dict, List, Optional

import pyarrow as pa

import grpc

from . import spark_connect_subset_pb2 as pb

_SERVICE = "/spark.connect.SparkConnectService/"
_CLIENT_TYPE = "daft-tpu vendored pyspark-connect client"


# ------------------------------------------------------------ expressions

class Column:
    """pyspark.sql.Column lookalike building Spark Connect proto exprs."""

    def __init__(self, expr: pb.Expression):
        self._expr = expr

    @staticmethod
    def _lit(v) -> "Column":
        if isinstance(v, Column):
            return v
        lit = pb.Expression.Literal()
        if isinstance(v, bool):
            lit.boolean = v
        elif isinstance(v, int):
            lit.long = v
        elif isinstance(v, float):
            lit.double = v
        elif isinstance(v, str):
            lit.string = v
        else:
            raise TypeError(f"unsupported literal {type(v)}")
        return Column(pb.Expression(literal=lit))

    def _fn(self, name: str, *args) -> "Column":
        return Column(pb.Expression(
            unresolved_function=pb.Expression.UnresolvedFunction(
                function_name=name,
                arguments=[self._expr] + [Column._lit(a)._expr
                                          for a in args])))

    def __gt__(self, o): return self._fn(">", o)
    def __ge__(self, o): return self._fn(">=", o)
    def __lt__(self, o): return self._fn("<", o)
    def __le__(self, o): return self._fn("<=", o)
    def __eq__(self, o): return self._fn("==", o)  # noqa: comparison API
    def __ne__(self, o): return self._fn("!=", o)
    def __add__(self, o): return self._fn("+", o)
    def __sub__(self, o): return self._fn("-", o)
    def __mul__(self, o): return self._fn("*", o)
    def __truediv__(self, o): return self._fn("/", o)
    def __and__(self, o): return self._fn("and", o)
    def __or__(self, o): return self._fn("or", o)

    def alias(self, name: str) -> "Column":
        return Column(pb.Expression(alias=pb.Expression.Alias(
            expr=self._expr, name=[name])))


def col(name: str) -> Column:
    return Column(pb.Expression(
        unresolved_attribute=pb.Expression.UnresolvedAttribute(
            unparsed_identifier=name)))


def lit(v) -> Column:
    return Column._lit(v)


_DT_PRIMITIVES = {
    "null": pa.null(), "binary": pa.large_binary(), "boolean": pa.bool_(),
    "byte": pa.int8(), "short": pa.int16(), "integer": pa.int32(),
    "long": pa.int64(), "float": pa.float32(), "double": pa.float64(),
    "string": pa.large_string(), "date": pa.date32(),
    "timestamp": pa.timestamp("us", "UTC"),
    "timestamp_ntz": pa.timestamp("us"),
}


def _datatype_to_arrow(dt: "pb.DataType") -> pa.DataType:
    kind = dt.WhichOneof("kind")
    if kind in _DT_PRIMITIVES:
        return _DT_PRIMITIVES[kind]
    if kind == "decimal":
        return pa.decimal128(dt.decimal.precision or 38,
                             dt.decimal.scale or 0)
    if kind == "array":
        return pa.large_list(_datatype_to_arrow(dt.array.element_type))
    if kind == "map":
        return pa.map_(_datatype_to_arrow(dt.map.key_type),
                       _datatype_to_arrow(dt.map.value_type))
    if kind == "struct":
        return pa.struct([
            pa.field(f.name, _datatype_to_arrow(f.data_type),
                     nullable=f.nullable) for f in dt.struct.fields])
    raise NotImplementedError(f"DataType kind {kind!r}")


def _datatype_to_arrow_schema(dt: "pb.DataType") -> pa.Schema:
    """AnalyzePlan returns the root as a struct DataType — the same shape
    pyspark converts into its StructType; here it becomes a pa.Schema."""
    t = _datatype_to_arrow(dt)
    if not pa.types.is_struct(t):
        raise ValueError(f"schema root is {t}, expected struct")
    return pa.schema(list(t))


def _agg_fn(name: str, c: Column) -> Column:
    return Column(pb.Expression(
        unresolved_function=pb.Expression.UnresolvedFunction(
            function_name=name, arguments=[c._expr])))


# ---------------------------------------------------------------- session

class SparkSession:
    """pyspark.sql.SparkSession lookalike over the Connect wire."""

    def __init__(self, address: str):
        self._channel = grpc.insecure_channel(address)
        self._session_id = str(uuid.uuid4())
        self._user = pb.UserContext(user_id="daft_tpu", user_name="daft_tpu")

    # -- RPC plumbing (the pyspark client's request shapes) -------------
    def _execute_plan(self, plan: pb.Plan) -> pa.Table:
        stub = self._channel.unary_stream(
            _SERVICE + "ExecutePlan",
            request_serializer=pb.ExecutePlanRequest.SerializeToString,
            response_deserializer=pb.ExecutePlanResponse.FromString)
        req = pb.ExecutePlanRequest(
            session_id=self._session_id, user_context=self._user,
            operation_id=str(uuid.uuid4()), client_type=_CLIENT_TYPE,
            plan=plan)
        tables = []
        complete = False
        for resp in stub(req):
            kind = resp.WhichOneof("response_type")
            if kind == "arrow_batch":
                with pa.ipc.open_stream(
                        pa.BufferReader(resp.arrow_batch.data)) as r:
                    tables.append(r.read_all())
            elif kind == "result_complete":
                complete = True
        if not complete:
            raise RuntimeError("server stream ended without ResultComplete")
        if not tables:
            return pa.table({})
        return pa.concat_tables(tables)

    def _analyze(self, **kwargs) -> pb.AnalyzePlanResponse:
        stub = self._channel.unary_unary(
            _SERVICE + "AnalyzePlan",
            request_serializer=pb.AnalyzePlanRequest.SerializeToString,
            response_deserializer=pb.AnalyzePlanResponse.FromString)
        return stub(pb.AnalyzePlanRequest(
            session_id=self._session_id, user_context=self._user,
            client_type=_CLIENT_TYPE, **kwargs))

    # -- public API ------------------------------------------------------
    def range(self, end: int, start: int = 0, step: int = 1) -> "DataFrame":
        return DataFrame(self, pb.Relation(
            range=pb.Range(start=start, end=end, step=step)))

    def sql(self, query: str) -> "DataFrame":
        return DataFrame(self, pb.Relation(sql=pb.SQL(query=query)))

    def createDataFrame(self, data: Dict[str, list]) -> "DataFrame":
        t = pa.table(data)
        buf = io.BytesIO()
        with pa.ipc.new_stream(buf, t.schema) as w:
            w.write_table(t)
        return DataFrame(self, pb.Relation(
            local_relation=pb.LocalRelation(data=buf.getvalue())))

    def read_parquet(self, path: str) -> "DataFrame":
        ds = pb.Read.DataSource(format="parquet", paths=[path])
        return DataFrame(self, pb.Relation(read=pb.Read(data_source=ds)))

    @property
    def version(self) -> str:
        r = self._analyze(spark_version=pb.AnalyzePlanRequest.SparkVersion())
        return r.spark_version.version

    def stop(self):
        self._channel.close()


def connect(address: str) -> SparkSession:
    return SparkSession(address)


# -------------------------------------------------------------- dataframe

class GroupedData:
    def __init__(self, df: "DataFrame", keys: List[Column]):
        self._df = df
        self._keys = keys

    def agg(self, *aggs: Column) -> "DataFrame":
        rel = pb.Relation(aggregate=pb.Aggregate(
            input=self._df._rel,
            group_type=pb.Aggregate.GROUP_TYPE_GROUPBY,
            grouping_expressions=[k._expr for k in self._keys],
            aggregate_expressions=[a._expr for a in aggs]))
        return DataFrame(self._df._session, rel)


class DataFrameWriter:
    def __init__(self, df: "DataFrame"):
        self._df = df

    def parquet(self, path: str, mode: str = "error"):  # pyspark default
        mode_map = {"overwrite": pb.WriteOperation.SAVE_MODE_OVERWRITE,
                    "append": pb.WriteOperation.SAVE_MODE_APPEND,
                    "error": pb.WriteOperation.SAVE_MODE_ERROR_IF_EXISTS,
                    "ignore": pb.WriteOperation.SAVE_MODE_IGNORE}
        cmd = pb.Command(write_operation=pb.WriteOperation(
            input=self._df._rel, source="parquet", path=path,
            mode=mode_map[mode]))
        self._df._session._execute_plan(pb.Plan(command=cmd))


class DataFrame:
    def __init__(self, session: SparkSession, rel: pb.Relation):
        self._session = session
        self._rel = rel

    def filter(self, cond: Column) -> "DataFrame":
        return DataFrame(self._session, pb.Relation(
            filter=pb.Filter(input=self._rel, condition=cond._expr)))

    where = filter

    def select(self, *cols) -> "DataFrame":
        exprs = [c._expr if isinstance(c, Column) else col(c)._expr
                 for c in cols]
        return DataFrame(self._session, pb.Relation(
            project=pb.Project(input=self._rel, expressions=exprs)))

    def withColumn(self, name: str, c: Column) -> "DataFrame":
        alias = pb.Expression.Alias(expr=c._expr, name=[name])
        return DataFrame(self._session, pb.Relation(
            with_columns=pb.WithColumns(
                input=self._rel, aliases=[alias])))

    def groupBy(self, *keys) -> GroupedData:
        ks = [k if isinstance(k, Column) else col(k) for k in keys]
        return GroupedData(self, ks)

    def join(self, other: "DataFrame", on: str,
             how: str = "inner") -> "DataFrame":
        how_map = {"inner": pb.Join.JOIN_TYPE_INNER,
                   "left": pb.Join.JOIN_TYPE_LEFT_OUTER,
                   "right": pb.Join.JOIN_TYPE_RIGHT_OUTER,
                   "outer": pb.Join.JOIN_TYPE_FULL_OUTER,
                   "semi": pb.Join.JOIN_TYPE_LEFT_SEMI,
                   "anti": pb.Join.JOIN_TYPE_LEFT_ANTI}
        return DataFrame(self._session, pb.Relation(join=pb.Join(
            left=self._rel, right=other._rel,
            join_type=how_map[how], using_columns=[on])))

    def sort(self, *keys: str) -> "DataFrame":
        SO = pb.Expression.SortOrder
        orders = [SO(child=col(k)._expr,
                     direction=SO.SORT_DIRECTION_ASCENDING)
                  for k in keys]
        return DataFrame(self._session, pb.Relation(
            sort=pb.Sort(input=self._rel, order=orders)))

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self._session, pb.Relation(
            limit=pb.Limit(input=self._rel, limit=n)))

    def createOrReplaceTempView(self, name: str):
        cmd = pb.Command(
            create_dataframe_view=pb.CreateDataFrameViewCommand(
                input=self._rel, name=name, replace=True))
        self._session._execute_plan(pb.Plan(command=cmd))

    @property
    def write(self) -> DataFrameWriter:
        return DataFrameWriter(self)

    @property
    def schema(self) -> pa.Schema:
        r = self._session._analyze(schema=pb.AnalyzePlanRequest.Schema(
            plan=pb.Plan(root=self._rel)))
        return _datatype_to_arrow_schema(r.schema.schema)

    def collect(self) -> List[dict]:
        return self.toArrow().to_pylist()

    def toArrow(self) -> pa.Table:
        return self._session._execute_plan(pb.Plan(root=self._rel))

    def toPandas(self):
        return self.toArrow().to_pandas()
