"""Window specification (reference: ``daft/window.py:12`` + daft-dsl
WindowSpec/WindowFrame)."""

from __future__ import annotations

from typing import List, Optional, Tuple, Union


class Window:
    """Builder for window specs: ``Window().partition_by("a").order_by("b")``.

    Frame bounds follow the reference: ``unbounded_preceding`` /
    ``unbounded_following`` class attributes and ``rows_between`` /
    ``range_between``.
    """

    unbounded_preceding = "unbounded_preceding"
    unbounded_following = "unbounded_following"
    current_row = 0

    def __init__(self):
        self._partition_by: List = []
        self._order_by: List = []
        self._descending: List[bool] = []
        self._nulls_first: List[bool] = []
        self._frame: Optional[Tuple[str, object, object]] = None
        self._min_periods: int = 1

    def _copy(self) -> "Window":
        w = Window()
        w._partition_by = list(self._partition_by)
        w._order_by = list(self._order_by)
        w._descending = list(self._descending)
        w._nulls_first = list(self._nulls_first)
        w._frame = self._frame
        w._min_periods = self._min_periods
        return w

    def partition_by(self, *cols) -> "Window":
        w = self._copy()
        for c in cols:
            if isinstance(c, (list, tuple)):
                w._partition_by.extend(c)
            else:
                w._partition_by.append(c)
        return w

    def order_by(self, *cols, desc: Union[bool, List[bool]] = False,
                 nulls_first: Optional[Union[bool, List[bool]]] = None
                 ) -> "Window":
        w = self._copy()
        flat = []
        for c in cols:
            if isinstance(c, (list, tuple)):
                flat.extend(c)
            else:
                flat.append(c)
        descs = [desc] * len(flat) if isinstance(desc, bool) else list(desc)
        if nulls_first is None:
            nfs = list(descs)
        elif isinstance(nulls_first, bool):
            nfs = [nulls_first] * len(flat)
        else:
            nfs = list(nulls_first)
        w._order_by.extend(flat)
        w._descending.extend(descs)
        w._nulls_first.extend(nfs)
        return w

    def rows_between(self, start="unbounded_preceding",
                     end="unbounded_following",
                     min_periods: int = 1) -> "Window":
        w = self._copy()
        w._frame = ("rows", start, end, min_periods)
        w._min_periods = min_periods
        return w

    def range_between(self, start="unbounded_preceding",
                      end="unbounded_following",
                      min_periods: int = 1) -> "Window":
        w = self._copy()
        w._frame = ("range", start, end, min_periods)
        w._min_periods = min_periods
        return w

    def __repr__(self):
        # the full spec must round-trip into repr: DataFrame.with_columns
        # groups window expressions by it, so omitting a field (e.g. sort
        # direction) would silently merge distinct specs
        return (f"Window(partition_by={self._partition_by}, "
                f"order_by={self._order_by}, desc={self._descending}, "
                f"nulls_first={self._nulls_first}, frame={self._frame}, "
                f"min_periods={self._min_periods})")
