"""FusedRegion planning — whole-query device compilation (round 21).

The translated physical plan executes operator-at-a-time: every stage
boundary is a host round-trip even when both sides run as device programs.
This pass walks the local physical plan bottom-up and greedily grows
*fusion regions* — maximal device-eligible operator chains — into
:class:`plan.FusedRegion` nodes the executor compiles as ONE donated-buffer
XLA program per size class (``device/fragment.py`` region compiler), so the
region's intermediates never materialize on host (HiFrames' whole-program
compilation argument, PAPERS.md).

Three region grammars, bounded by the r12 megakernel precedent:

- **chain**: ``Filter*/Project*`` over a scan — predicate + projection +
  in-program compaction, one packed transfer of survivors.
- **topk**: a chain with a ``TopN`` tail — the argsort runs in-program and
  only a static top-k bucket crosses the link.
- **join_agg**: partial ``Aggregate`` ← ``Project*/Filter*`` ← inner
  single-key broadcast ``HashJoin`` ← chain-over-scan probe side — the
  build side is encoded once and stays device-resident; each probe morsel
  joins, projects, and partially aggregates in one dispatch.

The planner only *proposes* regions: admission is priced per morsel by the
calibrated cost model (``costmodel.fusion_wins``), and every region keeps
its original subtree as ``fallback`` — fusion is an execution strategy,
never a semantics change.  ``DAFT_TPU_FUSION=0`` disables the pass, ``1``
force-admits, ``auto`` (default) prices each dispatch;
``DAFT_TPU_FUSION_MAX_OPS`` caps how many operators one region may absorb
(trace-size / retrace-surface bound).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..expressions import Expression, col
from ..schema import Schema
from . import plan as pp

#: static top-k tail bound: past this the "tiny bucket transfer" premise
#: is gone and the external sort is the right tool anyway
TOPK_MAX_LIMIT = 8192

#: partial-agg ops the region compiler's grouped reduction supports
#: (mirrors fragment.get_fused_agg's whitelist)
_REGION_AGGS = ("sum", "mean", "min", "max", "count", "stddev", "var",
                "any_value", "bool_and", "bool_or")


def fusion_mode(cfg=None) -> str:
    """``auto`` | ``1`` | ``0`` (normalized)."""
    from ..analysis import knobs
    if cfg is not None:
        mode = str(getattr(cfg, "tpu_fusion", "auto") or "auto")
    else:
        mode = "auto"
    env = knobs.env_str("DAFT_TPU_FUSION", None)
    if env is not None:
        mode = env
    mode = mode.strip().lower()
    if mode in ("0", "off", "false"):
        return "0"
    if mode in ("1", "force", "true"):
        return "1"
    return "auto"


def max_region_ops(cfg=None) -> int:
    from ..analysis import knobs
    env = knobs.env_int("DAFT_TPU_FUSION_MAX_OPS", None)
    if env is not None:
        return max(int(env), 2)
    if cfg is not None:
        return max(int(getattr(cfg, "tpu_fusion_max_ops", 8) or 8), 2)
    return 8


def fuse_regions(plan: pp.PhysicalPlan, cfg) -> pp.PhysicalPlan:
    """Rewrite the translated physical plan, replacing eligible subtrees
    with FusedRegion nodes. Identity-memoized so SHARED subplans (translate's
    semantic-id dedup) stay shared after the rewrite."""
    if fusion_mode(cfg) == "0":
        return plan
    from ..device import runtime as drt
    if not drt.device_enabled():
        return plan
    memo: dict = {}
    return _walk(plan, cfg, memo)


def _walk(node: pp.PhysicalPlan, cfg, memo: dict) -> pp.PhysicalPlan:
    hit = memo.get(id(node))
    if hit is not None:
        return hit
    region = _match(node, cfg)
    out = region if region is not None else node
    if region is None:
        # only descend when the node itself did not fuse: a region's
        # fallback keeps the ORIGINAL children untouched
        node.children = [_walk(c, cfg, memo) for c in node.children]
    memo[id(node)] = out
    return out


def _match(node: pp.PhysicalPlan, cfg) -> Optional[pp.FusedRegion]:
    # shared subtrees materialize once and stream to every consumer —
    # folding one into a region would re-execute it per consumer
    if getattr(node, "shared_consumers", 1) > 1:
        return None
    if isinstance(node, pp.TopN):
        return _match_topk(node, cfg)
    if isinstance(node, (pp.Project, pp.Filter)):
        return _match_chain(node, cfg)
    if isinstance(node, pp.Aggregate):
        return _match_join_agg(node, cfg)
    return None


# ------------------------------------------------------------------ chains

def _collect_chain(n: pp.PhysicalPlan, max_ops: int):
    """Walk a Filter*/Project* chain down to a source. Returns
    ``(source, chain_top_down)`` or None. Stops at shared interior nodes
    (materialize-once contract) and at the region-size cap."""
    chain: List[pp.PhysicalPlan] = []
    while isinstance(n, (pp.Project, pp.Filter)) \
            and getattr(n, "shared_consumers", 1) <= 1 \
            and len(chain) < max_ops:
        chain.append(n)
        n = n.children[0]
    if not isinstance(n, (pp.ScanSource, pp.InMemorySource)):
        return None
    return n, chain


def _substitute_chain(source, chain, out_names: List[str]):
    """Fold a top-down Project/Filter chain into (exprs, predicate) over
    SOURCE columns (the r12 substitution discipline). Returns
    ``(exprs, predicate)`` or None when an expression resists
    substitution."""
    from ..logical.optimizer import combine_conjuncts, substitute_columns
    mapping = {c: col(c) for c in source.schema().column_names}
    preds = []
    try:
        for nd in reversed(chain):
            if isinstance(nd, pp.Filter):
                preds.append(substitute_columns(nd.predicate, mapping))
            else:
                mapping = {e.name(): substitute_columns(e._unalias(), mapping)
                           for e in nd.exprs}
        exprs = [mapping[nm].alias(nm) if nm in mapping else None
                 for nm in out_names]
        if any(e is None for e in exprs):
            return None
        pred = combine_conjuncts(preds) if preds else None
    except Exception:
        return None
    return exprs, pred


def _decodable(field, expr: Expression) -> bool:
    """Region outputs must come back through the packed transfer: device
    repr, or a string/binary passthrough riding its source dictionary."""
    from ..device import runtime as drt
    if field.dtype.is_string() or field.dtype.is_binary():
        return drt._string_out_source(expr) is not None
    return field.dtype.device_repr() is not None


def _match_chain(node, cfg) -> Optional[pp.FusedRegion]:
    found = _collect_chain(node, max_region_ops(cfg))
    if found is None:
        return None
    source, chain = found
    # a single projection/filter is the per-operator path already — a
    # region only pays off when it ELIMINATES a stage boundary
    if len(chain) < 2:
        return None
    out_names = node.schema().column_names
    sub = _substitute_chain(source, chain, out_names)
    if sub is None:
        return None
    exprs, pred = sub
    schema = node.schema()
    try:
        for e, nm in zip(exprs, out_names):
            if not _decodable(schema[nm], e):
                return None
    except Exception:
        return None
    names = tuple(type(nd).__name__.lower() for nd in chain) + ("scan",)
    return pp.FusedRegion("chain", source, exprs, pred, schema,
                          fallback=node, fused_ops=names)


def _match_topk(node: pp.TopN, cfg) -> Optional[pp.FusedRegion]:
    if not node.sort_by or node.limit is None \
            or not (0 < node.limit <= TOPK_MAX_LIMIT):
        return None
    found = _collect_chain(node.children[0], max_region_ops(cfg) - 1)
    if found is None:
        return None
    source, chain = found
    # unlike plain chains a bare TopN-over-scan already saves the full-
    # table transfer (argsort in-program, static k bucket out), so an
    # empty chain still fuses
    out_names = node.schema().column_names
    sub = _substitute_chain(source, chain, out_names)
    if sub is None:
        return None
    exprs, pred = sub
    sub_keys = _substitute_chain(source, chain,
                                 [e.name() for e in node.sort_by])
    if sub_keys is None:
        return None
    sort_exprs = sub_keys[0]
    schema = node.schema()
    try:
        for e, nm in zip(exprs, out_names):
            if not _decodable(schema[nm], e):
                return None
        src_schema = source.schema()
        for e in sort_exprs:
            f = e.to_field(src_schema)
            if f.dtype.is_string() or f.dtype.is_binary():
                from ..device import runtime as drt
                if drt._string_out_source(e) is None:
                    return None
            elif f.dtype.device_repr() is None:
                return None
    except Exception:
        return None
    names = ("topn",) + tuple(type(nd).__name__.lower() for nd in chain) \
        + ("scan",)
    return pp.FusedRegion(
        "topk", source, exprs, pred, schema, fallback=node, fused_ops=names,
        sort_by=tuple(sort_exprs),
        descending=tuple(bool(d) for d in node.descending),
        nulls_first=tuple(bool(x) for x in node.nulls_first),
        limit=int(node.limit))


# ---------------------------------------------------------------- join_agg

def _match_join_agg(node: pp.Aggregate, cfg) -> Optional[pp.FusedRegion]:
    """Partial-Agg ← Project*/Filter* ← inner single-key broadcast
    HashJoin ← chain-over-scan probe. The build subplan executes on host
    (it is small — that is what made it broadcast) and is encoded ONCE;
    probe morsels stream through the single fused program."""
    from ..aggs import split_agg_expr
    from ..logical.optimizer import combine_conjuncts, substitute_columns
    if node.mode != "partial" or not node.group_by:
        return None
    max_ops = max_region_ops(cfg)
    mid: List[pp.PhysicalPlan] = []
    n = node.children[0]
    while isinstance(n, (pp.Project, pp.Filter)) \
            and getattr(n, "shared_consumers", 1) <= 1 \
            and len(mid) < max_ops:
        mid.append(n)
        n = n.children[0]
    if not isinstance(n, pp.HashJoin) or n.how != "inner" \
            or n.strategy != "broadcast_right" \
            or getattr(n, "shared_consumers", 1) > 1:
        return None
    if len(n.left_on) != 1 or len(n.right_on) != 1:
        return None
    join = n
    found = _collect_chain(join.children[0], max_ops)
    if found is None:
        return None
    source, probe_chain = found
    if len(mid) + len(probe_chain) + 3 > max_ops:
        return None
    build = join.children[1]

    # join keys must be passthrough int-ish columns: the in-program join
    # compares raw planes, and string codes are NOT comparable across two
    # independently encoded tables
    src_schema = source.schema()
    build_schema = build.schema()

    def _key_col(e: Expression, schema: Schema) -> Optional[str]:
        inner = e._unalias()
        if inner.op != "col":
            return None
        nm = inner.params[0]
        try:
            dt = schema[nm].dtype
        except Exception:
            return None
        if dt.is_string() or dt.is_binary() or dt.device_repr() is None:
            return None
        return nm

    # probe-side join key substituted through the probe chain
    sub_key = _substitute_chain(source, probe_chain,
                                [e.name() for e in join.left_on])
    if sub_key is None:
        return None
    lkey = _key_col(sub_key[0][0], src_schema)
    rkey = _key_col(join.right_on[0], build_schema)
    if lkey is None or rkey is None:
        return None

    # probe chain folds to (probe exprs, probe predicate) over source cols.
    # Joined-plane namespace = probe chain outputs ∪ build columns; names
    # must be disjoint or the substitution would be ambiguous.
    probe_out = join.children[0].schema().column_names
    build_out = build_schema.column_names
    if set(probe_out) & set(build_out):
        return None
    # the program's joined plane dict is keyed by RAW column name over
    # src ∪ build schemas; a shared name would alias two planes (and
    # break the needs-cols split in get_fused_join_agg) — decline
    if set(src_schema.column_names) & set(build_out):
        return None
    sub_probe = _substitute_chain(source, probe_chain, probe_out)
    if sub_probe is None:
        return None
    probe_exprs, probe_pred = sub_probe
    probe_map = {nm: e._unalias() for nm, e in zip(probe_out, probe_exprs)}
    # every probe-side joined column must be a source passthrough: the
    # program gathers RAW source planes by the join's left index, so a
    # computed projection would be lost (computed cols ride the mid-chain
    # substitution below instead, evaluated AFTER the gather)
    for nm, e in probe_map.items():
        if e.op != "col":
            return None

    # mid chain (between join and agg) folds over the joined namespace
    mapping = {nm: col(probe_map[nm].params[0]) for nm in probe_out}
    mapping.update({nm: col(nm) for nm in build_out})
    post_preds: List[Expression] = []
    try:
        for nd in reversed(mid):
            if isinstance(nd, pp.Filter):
                post_preds.append(substitute_columns(nd.predicate, mapping))
            else:
                mapping = {e.name(): substitute_columns(e._unalias(), mapping)
                           for e in nd.exprs}
        gb2 = [substitute_columns(e._unalias(), mapping).alias(e.name())
               for e in node.group_by]
        aggs2 = []
        for a in node.aggs:
            op, child, name, params = split_agg_expr(a)
            if op not in _REGION_AGGS:
                return None
            if op == "count" and params and params[0] != "valid":
                return None
            c2 = substitute_columns(child, mapping) if child is not None \
                else None
            inner = Expression("agg." + op, (c2,) if c2 is not None else (),
                               params)
            aggs2.append(inner.alias(name))
        post_pred = combine_conjuncts(post_preds) if post_preds else None
    except Exception:
        return None

    # outputs must decode without per-table dictionaries: string planes
    # gathered across the join would need dictionary routing the packed
    # block does not carry — decline them (q3's keys are ints/dates)
    p1_schema = node.schema()
    try:
        for g in gb2:
            f = p1_schema[g.name()]
            if f.dtype.is_string() or f.dtype.is_binary() \
                    or f.dtype.device_repr() is None:
                return None
        for a in aggs2:
            f = p1_schema[a.name()]
            if f.dtype.is_string() or f.dtype.is_binary() \
                    or f.dtype.device_repr() is None:
                return None
    except Exception:
        return None
    names = ("aggregate",) \
        + tuple(type(nd).__name__.lower() for nd in mid) + ("hashjoin",) \
        + tuple(type(nd).__name__.lower() for nd in probe_chain) + ("scan",)
    region = pp.FusedRegion(
        "join_agg", source, [], probe_pred, p1_schema, fallback=node,
        fused_ops=names, build=build,
        left_on=(col(lkey),), right_on=(col(rkey),),
        aggs=tuple(aggs2), group_by=tuple(gb2), mode="partial")
    region.post_predicate = post_pred
    # the original (pre-fusion) estimate evidence rides along for the gate
    region.group_ndv = getattr(node, "group_ndv", None)
    region.group_rows_est = getattr(node, "group_rows_est", None)
    return region
