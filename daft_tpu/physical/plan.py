"""Physical plan nodes (reference: ``src/daft-local-plan/src/plan.rs:20`` —
~30 variants — plus the distributed exchange ops of
``src/daft-physical-plan/src/plan.rs:18-52``)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ..expressions import Expression
from ..schema import Schema


class PhysicalPlan:
    def __init__(self, children: List["PhysicalPlan"], schema: Schema):
        self.children = children
        self._schema = schema

    def schema(self) -> Schema:
        return self._schema

    def name(self) -> str:
        return type(self).__name__


class ScanSource(PhysicalPlan):
    def __init__(self, tasks: List[Any], schema: Schema):
        super().__init__([], schema)
        self.tasks = tasks


class InMemorySource(PhysicalPlan):
    def __init__(self, partitions: List[Any], schema: Schema):
        super().__init__([], schema)
        self.partitions = partitions


class Project(PhysicalPlan):
    def __init__(self, child, exprs: List[Expression], schema: Schema):
        super().__init__([child], schema)
        self.exprs = exprs


class UDFProject(PhysicalPlan):
    def __init__(self, child, exprs: List[Expression], schema: Schema,
                 concurrency: Optional[int]):
        super().__init__([child], schema)
        self.exprs = exprs
        self.concurrency = concurrency


class Filter(PhysicalPlan):
    def __init__(self, child, predicate: Expression):
        super().__init__([child], child.schema())
        self.predicate = predicate


class Limit(PhysicalPlan):
    def __init__(self, child, limit: int, offset: int = 0):
        super().__init__([child], child.schema())
        self.limit = limit
        self.offset = offset


class Explode(PhysicalPlan):
    def __init__(self, child, exprs, schema):
        super().__init__([child], schema)
        self.exprs = exprs


class Unpivot(PhysicalPlan):
    def __init__(self, child, ids, values, variable_name, value_name, schema):
        super().__init__([child], schema)
        self.ids = ids
        self.values = values
        self.variable_name = variable_name
        self.value_name = value_name


class Sample(PhysicalPlan):
    def __init__(self, child, fraction, size, with_replacement, seed):
        super().__init__([child], child.schema())
        self.fraction = fraction
        self.size = size
        self.with_replacement = with_replacement
        self.seed = seed


class MonotonicallyIncreasingId(PhysicalPlan):
    def __init__(self, child, column_name, schema):
        super().__init__([child], schema)
        self.column_name = column_name


class Aggregate(PhysicalPlan):
    """One aggregation stage. mode: single | partial | final."""

    def __init__(self, child, aggs, group_by, schema, mode: str = "single"):
        super().__init__([child], schema)
        self.aggs = aggs
        self.group_by = group_by
        self.mode = mode
        # estimate fields (advisory, rewritable by AQE/replan from
        # measurements — see analysis/plan_contracts.py): expected output
        # rows and group-key NDV, set by the translator on final-mode aggs
        self.group_rows_est: Optional[int] = None
        self.group_ndv: Optional[int] = None


class DeviceFragmentAgg(PhysicalPlan):
    """Fused scan→filter→project→partial-agg fragment: one XLA program per
    morsel (see device/fragment.py). Falls back to the equivalent host chain
    per-batch when a batch is not device-representable."""

    def __init__(self, source, predicate, aggs, group_by, schema, mode):
        super().__init__([source], schema)
        self.predicate = predicate
        self.aggs = aggs          # substituted over source columns
        self.group_by = group_by  # substituted over source columns
        self.mode = mode


class DeviceExchangeAgg(PhysicalPlan):
    """Mesh-collective shuffle+final-aggregate: the partial group blocks from
    the child are sharded over the device mesh, exchanged by key hash with
    ``lax.all_to_all`` over ICI, and final-merged — all inside one jit
    program (parallel/exchange.py ``sharded_grouped_agg``). Replaces the
    host Exchange(hash) + final Aggregate pair when key/value dtypes are
    device-representable and every final op is mesh-mergeable. Yields one
    partition per mesh shard (disjoint key sets). Falls back to the host
    pair at runtime if encoding fails."""

    def __init__(self, child, aggs, group_by, schema):
        super().__init__([child], schema)
        self.aggs = aggs          # final-merge aggs over partial columns
        self.group_by = group_by


class FusedRegion(PhysicalPlan):
    """A maximal device-eligible operator chain compiled as ONE XLA program
    (round 21 whole-query compilation, ``physical/fusion.py``). Intermediate
    tables never materialize on host: the region's operators share device-
    resident planes inside a single traced program, and only the region's
    output crosses the link.

    ``shape`` picks the region grammar:

    - ``chain``  — row-local Filter*/Project* over a source: predicate +
      projection eval + in-program compaction, one packed transfer of the
      surviving rows.
    - ``topk``   — a chain with a TopN tail: the argsort runs in-program and
      only a static top-k bucket is transferred.
    - ``join_agg`` — inner single-key equi-join spine feeding Project* and a
      partial grouped aggregation: the broadcast build side is encoded once
      and stays device-resident; each probe morsel joins, projects and
      partially aggregates in one dispatch (dual overflow ladders: join
      pair capacity and group bucket).

    ``fallback`` keeps the original unfused subtree — the executor runs it
    verbatim whenever the region declines (cost gate, encode failure,
    pyobject inputs), so fusion is strictly an execution strategy, never a
    semantics change.
    """

    def __init__(self, shape: str, source, exprs, predicate, schema,
                 fallback, fused_ops: Tuple[str, ...] = (),
                 sort_by=(), descending=(), nulls_first=(), limit=None,
                 build=None, left_on=(), right_on=(),
                 aggs=(), group_by=(), mode: str = "partial"):
        children = [source] + ([build] if build is not None else [])
        super().__init__(children, schema)
        self.shape = shape            # chain | topk | join_agg
        self.source = source          # probe-side ScanSource/InMemorySource
        self.exprs = exprs            # outputs, substituted over source cols
        self.predicate = predicate    # combined row-local conjuncts (or None)
        self.fallback = fallback      # original unfused subtree root
        self.fused_ops = fused_ops    # operator names folded into the region
        # topk tail
        self.sort_by = sort_by
        self.descending = descending
        self.nulls_first = nulls_first
        self.limit = limit
        # join_agg spine
        self.build = build            # broadcast build-side subplan
        self.left_on = left_on        # probe-side join keys
        self.right_on = right_on      # build-side join keys
        self.aggs = aggs              # partial aggs over joined columns
        self.group_by = group_by      # group keys over joined columns
        self.mode = mode
        # estimate fields carried over from the folded Aggregate
        self.group_rows_est: Optional[int] = None
        self.group_ndv: Optional[int] = None


class Dedup(PhysicalPlan):
    def __init__(self, child, on):
        super().__init__([child], child.schema())
        self.on = on


class Pivot(PhysicalPlan):
    def __init__(self, child, group_by, pivot_col, value_col, names, schema):
        super().__init__([child], schema)
        self.group_by = group_by
        self.pivot_col = pivot_col
        self.value_col = value_col
        self.names = names


class Window(PhysicalPlan):
    def __init__(self, child, window_exprs, partition_by, order_by,
                 descending, nulls_first, frame, schema):
        super().__init__([child], schema)
        self.window_exprs = window_exprs
        self.partition_by = partition_by
        self.order_by = order_by
        self.descending = descending
        self.nulls_first = nulls_first
        self.frame = frame


class Sort(PhysicalPlan):
    def __init__(self, child, sort_by, descending, nulls_first):
        super().__init__([child], child.schema())
        self.sort_by = sort_by
        self.descending = descending
        self.nulls_first = nulls_first


class TopN(PhysicalPlan):
    def __init__(self, child, sort_by, descending, nulls_first, limit):
        super().__init__([child], child.schema())
        self.sort_by = sort_by
        self.descending = descending
        self.nulls_first = nulls_first
        self.limit = limit


class Exchange(PhysicalPlan):
    """Repartition boundary — the TPU analogue of ShuffleExchange
    (``ops/shuffle_exchange.rs:41-58``); strategy chosen by the runner:
    in-process for the local runner, ICI all_to_all / host gRPC for the
    distributed runner."""

    def __init__(self, child, kind: str, num_partitions: int,
                 by: Tuple[Expression, ...] = (),
                 descending: Tuple[bool, ...] = (),
                 engine_inserted: bool = False):
        super().__init__([child], child.schema())
        self.kind = kind          # hash | random | range | split | gather
        self.num_partitions = num_partitions
        self.by = by
        self.descending = descending
        # engine-inserted shuffles (agg/join co-partitioning) may be
        # re-sized by AQE from ACTUAL materialized bytes; user-requested
        # repartitions keep their exact count
        self.engine_inserted = engine_inserted
        # estimate field: marks exchanges feeding a hash-join side so the
        # executor can detect co-partitioned inputs
        self.join_side = False


class StageInput(PhysicalPlan):
    """Leaf standing for another stage's exchanged output (flotilla's
    PreviousStageScan / InMemory pipeline-node seam,
    ``src/daft-physical-plan/src/plan.rs`` PreviousStageScan). The executor
    resolves it from the stage-input bindings passed at run time."""

    def __init__(self, stage_id: int, schema: Schema):
        super().__init__([], schema)
        self.stage_id = stage_id


class Concat(PhysicalPlan):
    def __init__(self, left, right):
        super().__init__([left, right], left.schema())


class HashJoin(PhysicalPlan):
    def __init__(self, left, right, left_on, right_on, how, schema,
                 strategy: str = "hash"):
        super().__init__([left, right], schema)
        self.left_on = left_on
        self.right_on = right_on
        self.how = how
        self.strategy = strategy  # hash | broadcast_right | broadcast_left
        # estimate fields: planner-side byte estimates, rewritten by the
        # distributed re-planner from measured materializations
        self.left_bytes_est: Optional[int] = None
        self.right_bytes_est: Optional[int] = None


class CrossJoin(PhysicalPlan):
    def __init__(self, left, right, schema):
        super().__init__([left, right], schema)


class Write(PhysicalPlan):
    def __init__(self, child, info: Dict, schema: Schema):
        super().__init__([child], schema)
        self.info = info
