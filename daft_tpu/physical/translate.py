"""Logical → physical translation.

Reference: ``src/daft-local-plan/src/translate.rs:19-434`` (direct lowering,
Aggregate → partial/final split) and
``src/daft-physical-plan/src/physical_planner/translate.rs:639,914``
(``populate_aggregation_stages``, shuffle insertion, broadcast-join decision
by size threshold).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..context import get_context
from ..datatype import DataType
from ..expressions import Expression, col, lit
from ..logical import plan as lp
from ..logical import stats as lstats
from ..schema import Schema
from . import plan as pp

# aggs outside the decomposition table cannot be split into partial/final
# stages → single-stage agg (single-sourced with the pipeline reducer and
# the distributed map-side combine: ``aggs.AGG_DECOMPOSITION`` is the
# decomposition table of record)
from ..aggs import AGG_DECOMPOSITION as _DECOMPOSABLE


import threading as _threading

_tl = _threading.local()


def translate(plan: lp.LogicalPlan) -> pp.PhysicalPlan:
    """Logical → physical, deduplicating SHARED subplans: logically equal
    subtrees (by ``semantic_id``) map to one physical node whose
    ``shared_consumers`` counts its parents — the executor materializes it
    once and streams the buffer to every consumer (TPC-H Q21's ``base``
    chain and late-lineitem dedup otherwise execute 2-3× each)."""
    cfg = get_context().execution_config
    fresh = not getattr(_tl, "active", False)
    if fresh:
        _tl.active = True
        _tl.memo = {}
    try:
        out = _t(plan, cfg)
        if fresh:
            # round 21: grow maximal device-eligible operator chains into
            # FusedRegion nodes (whole-query compilation) — outermost call
            # only, so nested stage translations rewrite exactly once
            from . import fusion
            out = fusion.fuse_regions(out, cfg)
        return out
    finally:
        if fresh:
            _tl.active = False
            _tl.memo = {}


def _nondeterministic(node: lp.LogicalPlan) -> bool:
    """True when the subtree's output is not a pure function of its
    inputs — e.g. an unseeded Sample. Such subtrees must never merge:
    two identical .sample() calls are independent draws."""
    if isinstance(node, lp.Sample) and node.seed is None:
        return True
    return any(_nondeterministic(c) for c in node.children)


def _t(node: lp.LogicalPlan, cfg) -> pp.PhysicalPlan:
    if getattr(_tl, "active", False):
        key = node.semantic_id()
        hit = _tl.memo.get(key)
        if hit is not None:
            hit.shared_consumers = getattr(hit, "shared_consumers", 1) + 1
            return hit
        out = _t_node(node, cfg)
        if not _nondeterministic(node):
            _tl.memo[key] = out
        return out
    return _t_node(node, cfg)


def _t_node(node: lp.LogicalPlan, cfg) -> pp.PhysicalPlan:
    if isinstance(node, lp.Source):
        if node.partitions is not None:
            return pp.InMemorySource(node.partitions, node.schema())
        tasks = getattr(node, "materialized_tasks", None)
        if tasks is None:
            tasks = node.scan_op.to_scan_tasks(node.pushdowns)
        return pp.ScanSource(tasks, node.schema())
    if isinstance(node, lp.Project):
        return pp.Project(_t(node.children[0], cfg), node.exprs, node.schema())
    if isinstance(node, lp.UDFProject):
        return pp.UDFProject(_t(node.children[0], cfg), node.exprs,
                             node.schema(), node.concurrency)
    if isinstance(node, lp.Filter):
        return pp.Filter(_t(node.children[0], cfg), node.predicate)
    if isinstance(node, lp.Limit):
        return pp.Limit(_t(node.children[0], cfg), node.limit, node.offset)
    if isinstance(node, lp.Explode):
        return pp.Explode(_t(node.children[0], cfg), node.exprs, node.schema())
    if isinstance(node, lp.Unpivot):
        return pp.Unpivot(_t(node.children[0], cfg), node.ids, node.values,
                          node.variable_name, node.value_name, node.schema())
    if isinstance(node, lp.Sample):
        return pp.Sample(_t(node.children[0], cfg), node.fraction, node.size,
                         node.with_replacement, node.seed)
    if isinstance(node, lp.MonotonicallyIncreasingId):
        return pp.MonotonicallyIncreasingId(_t(node.children[0], cfg),
                                            node.column_name, node.schema())
    if isinstance(node, lp.Sort):
        return pp.Sort(_t(node.children[0], cfg), node.sort_by,
                       node.descending, node.nulls_first)
    if isinstance(node, lp.TopN):
        return pp.TopN(_t(node.children[0], cfg), node.sort_by,
                       node.descending, node.nulls_first, node.limit)
    if isinstance(node, lp.Repartition):
        child = _t(node.children[0], cfg)
        spec = node.spec
        kind = {"hash": "hash", "random": "random", "range": "range",
                "unknown": "split"}[spec.kind]
        return pp.Exchange(child, kind, spec.num_partitions, spec.by,
                           spec.descending)
    if isinstance(node, lp.Distinct):
        child = _t(node.children[0], cfg)
        on = node.on or [col(n) for n in node.schema().column_names]
        ex = pp.Exchange(child, "hash", max(_nparts(node.children[0]), 1),
                         tuple(on), engine_inserted=True)
        return pp.Dedup(ex, on)
    if isinstance(node, lp.Aggregate):
        return _translate_agg(node, cfg)
    if isinstance(node, lp.Pivot):
        child = _t(node.children[0], cfg)
        gather = pp.Exchange(child, "gather", 1)
        return pp.Pivot(gather, node.group_by, node.pivot_col, node.value_col,
                        node.names, node.schema())
    if isinstance(node, lp.Window):
        child = _t(node.children[0], cfg)
        if node.partition_by:
            child = pp.Exchange(child, "hash", _nparts(node.children[0]),
                                tuple(node.partition_by))
        else:
            child = pp.Exchange(child, "gather", 1)
        return pp.Window(child, node.window_exprs, node.partition_by,
                         node.order_by, node.descending, node.nulls_first,
                         node.frame, node.schema())
    if isinstance(node, lp.Concat):
        return pp.Concat(_t(node.children[0], cfg), _t(node.children[1], cfg))
    if isinstance(node, lp.Join):
        return _translate_join(node, cfg)
    if isinstance(node, lp.Sink):
        child = _t(node.children[0], cfg)
        return pp.Write(child, node.info, node.schema())
    raise NotImplementedError(f"translate for {node.name()}")


def _nparts(node: lp.LogicalPlan) -> int:
    return max(node.num_partitions(), 1)


def _estimate_size(node: lp.LogicalPlan) -> Optional[int]:
    """Best-effort size estimate for join-strategy choice."""
    if isinstance(node, lp.Source):
        if node.partitions is not None:
            try:
                sz = getattr(node.partitions, "total_bytes", None)
                if sz is not None:
                    return sz
                return sum(p.size_bytes() or 0 for p in node.partitions)
            except Exception:
                return None
        tasks = getattr(node, "materialized_tasks", None)
        if tasks is None and node.scan_op is not None:
            tasks = node.scan_op.to_scan_tasks(node.pushdowns)
            node.materialized_tasks = tasks
        if tasks is not None:
            sizes = [t.size_bytes() for t in tasks]
            if all(s is not None for s in sizes):
                return sum(sizes)
        return None
    if isinstance(node, (lp.Filter, lp.Sample)):
        base = _estimate_size(node.children[0])
        return None if base is None else int(base * 0.2)
    if isinstance(node, lp.Limit):
        return 1024 * node.limit  # rough
    if isinstance(node, lp.Aggregate):
        base = _estimate_size(node.children[0])
        return None if base is None else max(int(base * 0.05), 1024)
    if isinstance(node, lp.Distinct):
        # DISTINCT on key columns often barely reduces (TPC-H Q21's
        # (orderkey, suppkey) pairs: 6M → 6M rows); pricing it like an
        # aggregation mispredicted a 100MB build side as broadcastable
        base = _estimate_size(node.children[0])
        return None if base is None else max(int(base * 0.5), 1024)
    if node.children:
        sizes = [_estimate_size(c) for c in node.children]
        if any(s is None for s in sizes):
            return None
        return sum(sizes)
    return None


def _translate_join(node: lp.Join, cfg) -> pp.PhysicalPlan:
    left, right = node.children
    pl, pr = _t(left, cfg), _t(right, cfg)
    if node.how == "cross":
        gather_r = pp.Exchange(pr, "gather", 1)
        return pp.CrossJoin(pl, gather_r, node.schema())
    lsize, rsize = _estimate_size(left), _estimate_size(right)
    threshold = cfg.broadcast_join_size_bytes_threshold
    strategy = node.strategy
    if strategy is None:
        if (rsize is not None and rsize <= threshold
                and node.how in ("inner", "left", "semi", "anti")):
            strategy = "broadcast_right"
        elif (lsize is not None and lsize <= threshold
              and node.how in ("inner", "right")):
            strategy = "broadcast_left"
        else:
            strategy = "hash"
    elif strategy == "broadcast":
        strategy = "broadcast_right" if node.how in ("inner", "left", "semi",
                                                     "anti") else "hash"
    if strategy == "sort_merge":
        # no exchanges here: the executor samples both sides and range-
        # partitions them with one shared boundary set (aligned-boundary
        # sort-merge, reference SortMergeJoin)
        return pp.HashJoin(pl, pr, node.left_on, node.right_on, node.how,
                           node.schema(), "sort_merge")
    if strategy == "hash" and (_nparts(left) > 1 or _nparts(right) > 1):
        n = max(_nparts(left), _nparts(right))
        # join-side exchanges are NOT count-adaptable (the two sides must
        # keep identical partition counts), but they ARE strategy-adaptable:
        # the executor's AQE path may demote the pair to a broadcast join
        # from measured sizes (reference: AdaptivePlanner re-planning joins
        # from materialized stats, planner.rs:451-640) — join_side marks
        # them as elidable.
        pl = pp.Exchange(pl, "hash", n, tuple(node.left_on))
        pr = pp.Exchange(pr, "hash", n, tuple(node.right_on))
        pl.join_side = True
        pr.join_side = True
    elif strategy == "broadcast_right":
        pr = pp.Exchange(pr, "gather", 1)
    elif strategy == "broadcast_left":
        pl = pp.Exchange(pl, "gather", 1)
    join = pp.HashJoin(pl, pr, node.left_on, node.right_on, node.how,
                       node.schema(), strategy)
    # footer-backed size evidence for the grace hash join's first-level
    # radix fanout (execution/out_of_core.plan_partitions): enough
    # buckets that each is EXPECTED to fit the pair budget — recursion
    # is the safety net when the estimate is wrong, not the plan
    join.left_bytes_est = lsize
    join.right_bytes_est = rsize
    return join


def _translate_agg(node: lp.Aggregate, cfg) -> pp.PhysicalPlan:
    from ..aggs import split_agg_expr
    child = node.children[0]
    pchild = _t(child, cfg)
    nparts = _nparts(child)
    specs = [split_agg_expr(e) for e in node.aggs]
    decomposable = all(op in _DECOMPOSABLE for op, _, _, _ in specs)

    if not decomposable:
        # gather everything and aggregate once
        if node.group_by:
            ex = pp.Exchange(pchild, "hash",
                             min(nparts, cfg.shuffle_aggregation_default_partitions),
                             tuple(node.group_by), engine_inserted=True)
        else:
            ex = pp.Exchange(pchild, "gather", 1)
        return pp.Aggregate(ex, node.aggs, node.group_by, node.schema(),
                            "single")

    partial_aggs, final_aggs, final_proj = _split_aggs(node, child.schema())
    p1_schema = _agg_schema(node.group_by, partial_aggs, child.schema())
    p1 = _try_fuse_partial(pchild, partial_aggs, node.group_by, p1_schema)
    if p1 is None:
        p1 = pp.Aggregate(pchild, partial_aggs, node.group_by, p1_schema,
                          "partial")
    gb2 = [col(e.name()) for e in node.group_by]
    f_schema = _agg_schema(gb2, final_aggs, p1_schema)
    est_rows = lstats.estimate(child).rows
    mesh_ex = _try_mesh_exchange_agg(p1, final_aggs, gb2, f_schema,
                                     p1_schema, est_rows)
    if mesh_ex is not None:
        p2 = mesh_ex
    else:
        if node.group_by:
            ex = pp.Exchange(
                p1, "hash",
                min(max(nparts, 1), cfg.shuffle_aggregation_default_partitions)
                if nparts > 1 else 1,
                tuple(col(e.name()) for e in node.group_by),
                engine_inserted=True)
        else:
            ex = pp.Exchange(p1, "gather", 1)
        p2 = pp.Aggregate(ex, final_aggs, gb2, f_schema, "final")
        # footer-backed output-cardinality estimate for the executor's
        # fused-dispatcher gate (max over keys is a lower bound on the
        # grouped output; enough for a decline-if-huge decision). The raw
        # row estimate rides along as the gate's fallback evidence: with
        # no footer stats (in-memory/CSV sources) it is an upper bound on
        # the group count, which is exactly what decline-if-huge needs.
        ndvs = [v for v in (lstats.column_ndv_footer(child, e.name(),
                                                     est_rows=est_rows)
                            for e in node.group_by) if v is not None]
        p2.group_ndv = max(ndvs) if ndvs else None
        p2.group_rows_est = est_rows
    proj = [col(e.name()) for e in node.group_by] + final_proj
    return pp.Project(p2, proj, node.schema())


def _try_mesh_exchange_agg(p1, final_aggs, gb2, f_schema: Schema,
                           p1_schema: Schema,
                           est_rows=None) -> Optional[pp.PhysicalPlan]:
    """Choose the ICI-collective shuffle+merge when statically sound: a
    multi-device mesh is up, the input is big enough to repay the
    collective program, every group key / partial value either
    round-trips the device encoding bit-exactly or is string/binary (those
    ride shared-dictionary codes — see ``_exchangeable``), and every final
    op merges with itself."""
    from ..aggs import split_agg_expr
    from ..device import column as dcol, runtime as drt
    from ..parallel import mesh as pmesh
    from ..parallel.exchange import MERGEABLE_OPS
    if not gb2:
        return None  # global aggs gather a handful of scalars — host wins
    if not drt.device_enabled() or pmesh.mesh_size() < 2:
        return None
    # admission is priced, not thresholded: the cost model compares the
    # collective (dispatch + bytes over the calibrated ICI rate) against
    # a host exchange pass over the estimated bytes; DAFT_TPU_MESH_MIN_ROWS
    # (when set) force-overrides with the old static row floor
    row_bytes = 8.0 * max(len(gb2) + len(final_aggs), 1)
    if not pmesh.mesh_admits(est_rows, row_bytes):
        return None
    def _exchangeable(dtype) -> bool:
        # bit-exact round trip, or string/binary riding shared dictionary
        # codes (the executor concats all partitions into one batch before
        # encoding, so every shard shares one sorted dictionary — codes are
        # comparable AND lexicographically ordered; see _np_plane_encoder)
        return (dcol.is_lossless_device_dtype(dtype)
                or dtype.is_string() or dtype.is_binary())

    for g in gb2:
        if not _exchangeable(p1_schema[g.name()].dtype):
            return None
    for a in final_aggs:
        op, child_e, name, params = split_agg_expr(a)
        if op not in MERGEABLE_OPS:
            return None
        if child_e is None or child_e._unalias().op != "col":
            return None
        if not _exchangeable(p1_schema[child_e._unalias().params[0]].dtype):
            return None
    return pp.DeviceExchangeAgg(p1, final_aggs, gb2, f_schema)


def _try_fuse_partial(pchild: pp.PhysicalPlan, partial_aggs, group_by,
                      p1_schema: Schema) -> Optional[pp.PhysicalPlan]:
    """Collapse partial-Agg ← Project* ← Filter* ← Scan into a fused device
    fragment, substituting intermediate projections so every expression is
    over source columns."""
    from ..aggs import split_agg_expr
    from ..logical.optimizer import combine_conjuncts, substitute_columns
    chain = []
    n = pchild
    while isinstance(n, (pp.Project, pp.Filter)):
        chain.append(n)
        n = n.children[0]
    # chain may be empty: fusing projection-exprs + agg over a bare source
    # still collapses to one program (scan-level filters prune earlier)
    if not isinstance(n, (pp.ScanSource, pp.InMemorySource)):
        return None
    mapping = {c: col(c) for c in n.schema().column_names}
    preds = []
    for node2 in reversed(chain):
        if isinstance(node2, pp.Filter):
            preds.append(substitute_columns(node2.predicate, mapping))
        else:
            try:
                mapping = {e.name(): substitute_columns(e._unalias(), mapping)
                           for e in node2.exprs}
            except Exception:
                return None
    try:
        gb2 = [substitute_columns(e._unalias(), mapping).alias(e.name())
               for e in group_by]
        aggs2 = []
        for a in partial_aggs:
            op, child, name, params = split_agg_expr(a)
            if op not in ("sum", "mean", "min", "max", "count", "stddev",
                          "var", "any_value", "bool_and", "bool_or"):
                return None
            if op == "count" and params and params[0] != "valid":
                return None
            c2 = substitute_columns(child, mapping) if child is not None \
                else None
            new_inner = Expression("agg." + op,
                                   (c2,) if c2 is not None else (), params)
            aggs2.append(new_inner.alias(name))
        pred = combine_conjuncts(preds) if preds else None
        # all agg outputs must be decodable without a dictionary
        for a in aggs2:
            f = p1_schema[a.name()]
            if f.dtype.device_repr() is None or f.dtype.is_string() \
                    or f.dtype.is_binary():
                return None
        # string group keys must be source-column passthroughs (their
        # dictionary travels from the encoded input)
        for g in gb2:
            f = p1_schema[g.name()]
            if (f.dtype.is_string() or f.dtype.is_binary()) \
                    and g._unalias().op != "col":
                return None
    except Exception:
        return None
    return pp.DeviceFragmentAgg(n, pred, aggs2, gb2, p1_schema, "partial")


def _agg_schema(group_by, aggs, input_schema: Schema) -> Schema:
    fields = [e.to_field(input_schema) for e in group_by]
    fields += [e.to_field(input_schema) for e in aggs]
    return Schema(fields)


def _split_aggs(node: lp.Aggregate, in_schema: Schema):
    """populate_aggregation_stages: per-agg partial exprs, final exprs over
    partial outputs, and the final projection."""
    partials: List[Expression] = []
    finals: List[Expression] = []
    projs: List[Expression] = []
    seen_partial = {}

    def add_partial(e: Expression) -> str:
        k = e._key()
        if k in seen_partial:
            return seen_partial[k]
        nm = e.name() if e.op == "alias" else f"__p{len(partials)}__{e.name()}"
        seen_partial[k] = nm
        partials.append(e.alias(nm) if e.name() != nm else e)
        return nm

    for e in node.aggs:
        out_name = e.name()
        inner = e._unalias()
        op = inner.op[4:]
        child = inner.args[0] if inner.args else None
        out_field = e.to_field(in_schema)
        if op in ("sum", "min", "max", "any_value", "bool_and", "bool_or",
                  "list", "concat"):
            p = add_partial(Expression(inner.op, inner.args, inner.params)
                            .alias(out_name))
            f_op = {"sum": "agg.sum", "min": "agg.min", "max": "agg.max",
                    "any_value": "agg.any_value", "bool_and": "agg.bool_and",
                    "bool_or": "agg.bool_or", "list": "agg.concat",
                    "concat": "agg.concat"}[op]
            finals.append(Expression(f_op, (col(p),),
                                     inner.params).alias(out_name))
            projs.append(col(out_name))
        elif op == "count":
            p = add_partial(inner.alias(out_name))
            finals.append(col(p).sum().alias(out_name))
            projs.append(col(out_name).cast(DataType.uint64()).alias(out_name))
        elif op == "mean":
            s = add_partial(child.sum().alias(f"__sum_{out_name}__"))
            c = add_partial(child.count().alias(f"__count_{out_name}__"))
            fs = f"__fsum_{out_name}__"
            fc = f"__fcount_{out_name}__"
            finals.append(col(s).sum().alias(fs))
            finals.append(col(c).sum().alias(fc))
            projs.append((col(fs).cast(DataType.float64())
                          / col(fc).cast(DataType.float64())).alias(out_name))
        elif op in ("stddev", "var"):
            s = add_partial(child.sum().alias(f"__sum_{out_name}__"))
            c = add_partial(child.count().alias(f"__count_{out_name}__"))
            s2 = add_partial((child * child).sum().alias(f"__sumsq_{out_name}__"))
            fs, fc, fs2 = (f"__fs_{out_name}__", f"__fc_{out_name}__",
                           f"__fs2_{out_name}__")
            finals.append(col(s).sum().alias(fs))
            finals.append(col(c).sum().alias(fc))
            finals.append(col(s2).sum().alias(fs2))
            mean = col(fs).cast(DataType.float64()) / col(fc).cast(DataType.float64())
            var = (col(fs2).cast(DataType.float64())
                   / col(fc).cast(DataType.float64())) - mean * mean
            projs.append((var.sqrt() if op == "stddev" else var).alias(out_name))
        else:
            raise NotImplementedError(f"agg split for {op}")
    return partials, finals, projs
