"""Adaptive query execution.

Reference: ``AdaptivePlanner`` (``src/daft-physical-plan/src/
physical_planner/planner.rs:451-640`` — ``next_stage`` / ``update_stats`` /
``explain_analyze``): stages materialize at exchange boundaries, ACTUAL
cardinalities feed back into planning of the remaining query. Here the
adaptivity acts on the same boundary the reference re-plans most profitably:
engine-inserted shuffles re-size their partition count from the measured
bytes of the materialized child (coalescing almost-empty shuffles to a few
partitions, capping giant ones at the configured target partition size),
and per-stage actuals are recorded for ``explain_analyze``.

Enable with ``DAFT_TPU_ENABLE_AQE=1`` / ``set_execution_config(enable_aqe=
True)``.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class StageStats:
    rows: int = 0
    size_bytes: int = 0
    partitions: int = 0
    decision: str = ""


class AdaptivePlanner:
    """Records per-boundary actuals and decides adapted partition counts."""

    def __init__(self, cfg):
        self.cfg = cfg
        self._lock = threading.Lock()
        self.history: List[StageStats] = []

    def adapt_partition_count(self, planned: int, total_bytes: int,
                              total_rows: int) -> int:
        """Engine-inserted shuffle → partition count sized from ACTUAL
        materialized bytes, bounded by the planned count."""
        target = max(self.cfg.target_partition_size_bytes, 1)
        by_size = max(math.ceil(total_bytes / target), 1)
        adapted = max(min(planned, by_size), 1)
        with self._lock:
            self.history.append(StageStats(
                rows=total_rows, size_bytes=total_bytes, partitions=adapted,
                decision=(f"shuffle {planned}→{adapted} parts "
                          f"({total_bytes} bytes materialized)")))
        return adapted

    def record_join(self, decision: str, measured_bytes: int) -> None:
        """Join-strategy adaptation from measured input sizes (hash ↔
        broadcast demotion)."""
        with self._lock:
            self.history.append(StageStats(
                rows=0, size_bytes=measured_bytes, partitions=0,
                decision=f"join {decision} "
                         f"({measured_bytes} bytes measured)"))

    def explain_analyze(self) -> str:
        lines = ["== Adaptive execution =="]
        with self._lock:
            for i, s in enumerate(self.history):
                lines.append(f"stage {i}: rows={s.rows} "
                             f"bytes={s.size_bytes} → {s.decision}")
        return "\n".join(lines)


_last: Optional[AdaptivePlanner] = None
_last_lock = threading.Lock()


def new_planner(cfg) -> AdaptivePlanner:
    global _last
    p = AdaptivePlanner(cfg)
    with _last_lock:
        _last = p
    return p


def last_planner() -> Optional[AdaptivePlanner]:
    return _last
