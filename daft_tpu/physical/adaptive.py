"""Adaptive query execution.

Reference: ``AdaptivePlanner`` (``src/daft-physical-plan/src/
physical_planner/planner.rs:451-640`` — ``next_stage`` / ``update_stats`` /
``explain_analyze``): stages materialize at exchange boundaries, ACTUAL
cardinalities feed back into planning of the remaining query. Three
adaptive layers compose here:

1. **Stage re-planning** (``runners/native_runner.py:_run_adaptive``):
   join inputs materialize cheapest-first; each one's measured rows/bytes
   replace its subtree as an in-memory source and the WHOLE optimizer
   re-runs over the remainder — join order (ReorderJoins with actuals)
   and broadcast-vs-hash flip from measurements. ``record_replan`` logs
   each round for explain_analyze.
2. **Shuffle resizing** (executor ``_exec_Exchange``): engine-inserted
   shuffles re-size partition counts from materialized bytes (coalescing
   almost-empty shuffles, capping giant ones at the target size).
3. **Join demotion** (executor ``_adaptive_hash_join``): a planned hash
   join whose measured input fits the broadcast threshold skips both
   shuffles.

The streaming spill-cache shuffle composes with all three (it simply
takes precedence over resizing at exchanges it serves).

Enable with ``DAFT_ENABLE_AQE=1`` (the ``ExecutionConfig.enable_aqe`` env
spelling — this docstring used to advertise a ``DAFT_TPU_``-prefixed AQE
knob that never existed; caught by the daft-lint knob registry) /
``set_execution_config(enable_aqe=True)``.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# ------------------------------------------------------ adaptive counters
# Process-wide accounting for BOTH feedback loops (round 20): calibration
# observations, distributed re-plan decisions (combine flips, broadcast
# demotions, exchange re-picks, estimate rewrites), and history
# evictions. Mirrors the shuffle/spill counter pattern: snapshot at query
# start, diff at finish() → the per-query ``adaptive`` stats block; also
# credited to the thread-attributed RuntimeStatsContext and scraped at
# ``/metrics`` as ``daft_tpu_adaptive_*_total``.

_counters_lock = threading.Lock()
_counters: Dict[str, float] = {}


def count(name: str, n: float = 1) -> None:
    with _counters_lock:
        _counters[name] = _counters.get(name, 0) + n
    from .. import observability as obs
    obs.bump_plane("adaptive", name, n)


def counters_snapshot() -> Dict[str, float]:
    with _counters_lock:
        return dict(_counters)


def counters_delta(before: Dict[str, float],
                   after: Optional[Dict[str, float]] = None
                   ) -> Dict[str, float]:
    if after is None:
        after = counters_snapshot()
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


def counters_reset() -> None:
    with _counters_lock:
        _counters.clear()


def history_cap() -> int:
    """Bound on ``AdaptivePlanner.history`` (``DAFT_TPU_ADAPTIVE_HISTORY``
    env, else the ``ExecutionConfig.tpu_adaptive_history`` mirror): the
    planner lives as long as its executor, and a long-lived serving
    process re-plans forever — an unbounded decision log is a slow leak."""
    from ..analysis import knobs
    cap = knobs.env_int("DAFT_TPU_ADAPTIVE_HISTORY", default=None)
    if cap is None:
        try:
            from ..context import get_context
            cap = int(get_context().execution_config.tpu_adaptive_history)
        except Exception:
            cap = 512
    return max(int(cap), 1)


@dataclass
class StageStats:
    rows: int = 0
    size_bytes: int = 0
    partitions: int = 0
    decision: str = ""


class AdaptivePlanner:
    """Records per-boundary actuals and decides adapted partition counts.

    ``history`` is BOUNDED (``DAFT_TPU_ADAPTIVE_HISTORY``): appends past
    the cap evict the oldest entry, counted in ``evictions`` (and the
    process-wide ``history_evictions`` adaptive counter) so a serving
    process that re-plans for days holds a window, not a log."""

    def __init__(self, cfg):
        self.cfg = cfg
        self._lock = threading.Lock()
        self.history: List[StageStats] = []
        self.evictions = 0
        self._cap = history_cap()

    def _append_locked(self, s: StageStats) -> None:
        self.history.append(s)
        while len(self.history) > self._cap:
            self.history.pop(0)
            self.evictions += 1
            count("history_evictions")

    def adapt_partition_count(self, planned: int, total_bytes: int,
                              total_rows: int) -> int:
        """Engine-inserted shuffle → partition count sized from ACTUAL
        materialized bytes, bounded by the planned count."""
        target = max(self.cfg.target_partition_size_bytes, 1)
        by_size = max(math.ceil(total_bytes / target), 1)
        adapted = max(min(planned, by_size), 1)
        with self._lock:
            self._append_locked(StageStats(
                rows=total_rows, size_bytes=total_bytes, partitions=adapted,
                decision=(f"shuffle {planned}→{adapted} parts "
                          f"({total_bytes} bytes materialized)")))
        return adapted

    def record_replan(self, decision: str, rows: int = 0,
                      size_bytes: int = 0) -> None:
        """Stage-level re-plan: a join input was materialized, its ACTUAL
        stats folded back into the logical plan, and the optimizer re-run
        over the remainder (the reference's update_stats → next_stage)."""
        with self._lock:
            self._append_locked(StageStats(
                rows=rows, size_bytes=size_bytes, partitions=0,
                decision=decision))

    def record_join(self, decision: str, measured_bytes: int) -> None:
        """Join-strategy adaptation from measured input sizes (hash ↔
        broadcast demotion)."""
        with self._lock:
            self._append_locked(StageStats(
                rows=0, size_bytes=measured_bytes, partitions=0,
                decision=f"join {decision} "
                         f"({measured_bytes} bytes measured)"))

    def explain_analyze(self) -> str:
        lines = ["== Adaptive execution =="]
        with self._lock:
            if self.evictions:
                lines.append(f"(history capped at {self._cap}; "
                             f"{self.evictions} oldest entries evicted)")
            for i, s in enumerate(self.history):
                lines.append(f"stage {i}: rows={s.rows} "
                             f"bytes={s.size_bytes} → {s.decision}")
        return "\n".join(lines)


_last: Optional[AdaptivePlanner] = None
_last_lock = threading.Lock()


def new_planner(cfg) -> AdaptivePlanner:
    global _last
    p = AdaptivePlanner(cfg)
    with _last_lock:
        _last = p
    return p


def last_planner() -> Optional[AdaptivePlanner]:
    return _last
