"""daft_tpu.native — ctypes bindings to the C++ host-kernel library.

The native tier covers the host data-plane hot spots that have no XLA
analogue: row hashing (reference ``src/daft-core/src/array/ops/hash.rs`` /
``src/daft-hash``), hash fanout partitioning (``ops/partition.rs:53-104``),
minhash (``src/daft-minhash``), HyperLogLog (``src/hyperloglog``), and
hash-join probe tables (``src/daft-recordbatch/src/probeable/``).

The shared library is compiled on first import with ``make`` (g++); if the
toolchain is unavailable the package falls back to the numpy implementations
(``AVAILABLE`` is False). Rebuilds happen automatically when ``kernels.cpp``
is newer than the built ``.so``.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import warnings

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libdaft_native.so")
_SRC = os.path.join(_DIR, "src", "kernels.cpp")
_STAMP = _SO + ".srchash"

AVAILABLE = False
_lib = None


def _src_hash() -> str:
    """Staleness stamp = source sha256 + host ISA fingerprint. The ISA part
    matters because we compile with -march=native: a .so carried to an older
    CPU (image copy, shared home dir) would SIGILL, so it must be rebuilt."""
    h = hashlib.sha256()
    with open(_SRC, "rb") as f:
        h.update(f.read())
    import platform
    h.update(platform.machine().encode())
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    h.update(line.encode())
                    break
    except OSError:
        pass
    return h.hexdigest()


def _build(src_hash: str) -> bool:
    """Compile to a temp file and atomically rename into place, so concurrent
    first imports (multi-process workers) never load a torn .so; the source
    hash stamp (not mtimes) decides staleness, so a foreign/stale binary from
    another machine is always rebuilt."""
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        r = subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-Wall",
             "-march=native", "-o", tmp, _SRC],
            capture_output=True, text=True, timeout=300)
        if r.returncode != 0:
            os.unlink(tmp)
            warnings.warn(f"daft_tpu.native build failed:\n{r.stderr[-2000:]}")
            return False
        os.rename(tmp, _SO)
        with open(_STAMP, "w") as f:
            f.write(src_hash)
        return True
    except (OSError, subprocess.TimeoutExpired) as e:
        warnings.warn(f"daft_tpu.native build failed: {e}")
        return False


def _load():
    global _lib, AVAILABLE
    from ..analysis import knobs
    if not knobs.env_bool("DAFT_TPU_NATIVE"):
        return
    src_hash = _src_hash()
    stamp = None
    if os.path.exists(_SO) and os.path.exists(_STAMP):
        with open(_STAMP) as f:
            stamp = f.read().strip()
    if stamp != src_hash and not _build(src_hash):
        return
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        warnings.warn(f"daft_tpu.native load failed: {e}")
        return

    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i64 = ctypes.c_int64
    u64 = ctypes.c_uint64

    lib.dn_xxh64.argtypes = [u8p, i64, u64]
    lib.dn_xxh64.restype = u64
    lib.dn_hash_fixed.argtypes = [u8p, i64, i64, u8p, u64, u64p]
    lib.dn_hash_var.argtypes = [i64p, u8p, i64, u8p, u64, u64p]
    lib.dn_hash_combine.argtypes = [u64p, u64p, i64, u64p]
    lib.dn_murmur3_32.argtypes = [u8p, i64, ctypes.c_uint32]
    lib.dn_murmur3_32.restype = ctypes.c_uint32
    lib.dn_fanout_hash.argtypes = [u64p, i64, i64, i64p, i64p, i64p]
    lib.dn_fanout_pid.argtypes = [i64p, i64, i64, i64p, i64p]
    lib.dn_minhash.argtypes = [i64p, u8p, i64, u8p, ctypes.c_int32,
                               ctypes.c_int32, u64, u32p]
    lib.dn_hll_add.argtypes = [u8p, ctypes.c_int32, u64p, i64]
    lib.dn_hll_merge.argtypes = [u8p, u8p, i64]
    lib.dn_hll_estimate.argtypes = [u8p, ctypes.c_int32]
    lib.dn_hll_estimate.restype = ctypes.c_double
    lib.dn_probe_build.argtypes = [u64p, i64]
    lib.dn_probe_build.restype = ctypes.c_void_p
    lib.dn_probe_run.argtypes = [ctypes.c_void_p, u64p, i64, i64p, i64p,
                                 i64, i64p]
    lib.dn_probe_run.restype = i64
    lib.dn_probe_free.argtypes = [ctypes.c_void_p]

    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.dn_bpe_build.argtypes = [i64p, u8p, i32p, i64]
    lib.dn_bpe_build.restype = ctypes.c_void_p
    lib.dn_bpe_encode.argtypes = [ctypes.c_void_p, u8p, i64, i32p]
    lib.dn_bpe_encode.restype = i64
    lib.dn_bpe_encode_batch.argtypes = [ctypes.c_void_p, i64p, u8p, i64,
                                        i32p, i64p]
    lib.dn_bpe_encode_batch.restype = i64
    lib.dn_bpe_free.argtypes = [ctypes.c_void_p]

    # daft-lint: allow(unguarded-global-mutation) -- import-time init:
    # _load() runs once at module bottom under the interpreter import lock
    _lib = lib
    # daft-lint: allow(unguarded-global-mutation) -- same import-time init
    AVAILABLE = True


_load()


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


_NULL_U8P = ctypes.POINTER(ctypes.c_uint8)()


def _valid_arr(valid):
    """Materialize the validity bitmap as uint8; the CALLER must hold the
    returned array for the duration of the C call (ctypes pointers do not
    keep their backing buffer alive)."""
    if valid is None:
        return None
    return np.ascontiguousarray(valid, dtype=np.uint8)


def _vp(valid_u8):
    return _NULL_U8P if valid_u8 is None else _ptr(valid_u8, ctypes.c_uint8)


def hash_fixed(data: np.ndarray, valid, seed: int = 0) -> np.ndarray:
    """xxh64 per fixed-width row. `data` is any contiguous 1-D numpy array."""
    data = np.ascontiguousarray(data)
    n = len(data)
    out = np.empty(n, dtype=np.uint64)
    valid_u8 = _valid_arr(valid)
    _lib.dn_hash_fixed(
        data.view(np.uint8).reshape(n, -1).ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint8)) if n else _NULL_U8P,
        n, data.itemsize, _vp(valid_u8), seed,
        _ptr(out, ctypes.c_uint64))
    return out


def hash_var(offsets: np.ndarray, data: np.ndarray, valid,
             seed: int = 0) -> np.ndarray:
    """xxh64 per variable-width row (Arrow large_binary layout)."""
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    n = len(offsets) - 1
    out = np.empty(n, dtype=np.uint64)
    valid_u8 = _valid_arr(valid)
    _lib.dn_hash_var(_ptr(offsets, ctypes.c_int64), _ptr(data, ctypes.c_uint8),
                     n, _vp(valid_u8), seed, _ptr(out, ctypes.c_uint64))
    return out


def hash_combine(h: np.ndarray, seed: np.ndarray) -> np.ndarray:
    h = np.ascontiguousarray(h, dtype=np.uint64)
    seed = np.ascontiguousarray(seed, dtype=np.uint64)
    out = np.empty(len(h), dtype=np.uint64)
    _lib.dn_hash_combine(_ptr(h, ctypes.c_uint64), _ptr(seed, ctypes.c_uint64),
                         len(h), _ptr(out, ctypes.c_uint64))
    return out


def fanout_hash(h: np.ndarray, nparts: int):
    """→ (counts[nparts], gather_indices[n]) — rows of partition p are
    indices[starts[p]:starts[p]+counts[p]] with starts = cumsum-exclusive."""
    h = np.ascontiguousarray(h, dtype=np.uint64)
    n = len(h)
    counts = np.empty(nparts, dtype=np.int64)
    indices = np.empty(n, dtype=np.int64)
    _lib.dn_fanout_hash(_ptr(h, ctypes.c_uint64), n, nparts,
                        _ptr(counts, ctypes.c_int64),
                        _ptr(indices, ctypes.c_int64),
                        ctypes.POINTER(ctypes.c_int64)())
    return counts, indices


def fanout_pid(pid: np.ndarray, nparts: int):
    pid = np.ascontiguousarray(pid, dtype=np.int64)
    n = len(pid)
    counts = np.empty(nparts, dtype=np.int64)
    indices = np.empty(n, dtype=np.int64)
    _lib.dn_fanout_pid(_ptr(pid, ctypes.c_int64), n, nparts,
                       _ptr(counts, ctypes.c_int64),
                       _ptr(indices, ctypes.c_int64))
    return counts, indices


def minhash(offsets: np.ndarray, data: np.ndarray, valid, num_hashes: int,
            ngram_size: int = 1, seed: int = 1) -> np.ndarray:
    """→ uint32 [n, num_hashes] minhash signature matrix."""
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    n = len(offsets) - 1
    out = np.empty((n, num_hashes), dtype=np.uint32)
    valid_u8 = _valid_arr(valid)
    _lib.dn_minhash(_ptr(offsets, ctypes.c_int64), _ptr(data, ctypes.c_uint8),
                    n, _vp(valid_u8), num_hashes, ngram_size, seed,
                    _ptr(out, ctypes.c_uint32))
    return out


class HyperLogLog:
    """Dense HLL accumulator over u64 hashes (default p=14 → 16Ki registers,
    ~0.8% relative error), mergeable across partitions/hosts."""

    def __init__(self, p: int = 14, registers: np.ndarray = None):
        self.p = p
        self.registers = registers if registers is not None \
            else np.zeros(1 << p, dtype=np.uint8)

    def add_hashes(self, hashes: np.ndarray) -> "HyperLogLog":
        hashes = np.ascontiguousarray(hashes, dtype=np.uint64)
        _lib.dn_hll_add(_ptr(self.registers, ctypes.c_uint8), self.p,
                        _ptr(hashes, ctypes.c_uint64), len(hashes))
        return self

    def merge(self, other: "HyperLogLog") -> "HyperLogLog":
        _lib.dn_hll_merge(_ptr(self.registers, ctypes.c_uint8),
                          _ptr(other.registers, ctypes.c_uint8),
                          len(self.registers))
        return self

    def estimate(self) -> float:
        return float(_lib.dn_hll_estimate(
            _ptr(self.registers, ctypes.c_uint8), self.p))


class ProbeTable:
    """Chained hash table over build-side row hashes; probing emits candidate
    (probe_idx, build_idx) pairs for exact-key verification by the caller."""

    def __init__(self, build_hashes: np.ndarray):
        h = np.ascontiguousarray(build_hashes, dtype=np.uint64)
        self._n_build = len(h)
        self._handle = _lib.dn_probe_build(_ptr(h, ctypes.c_uint64), len(h))

    def probe(self, probe_hashes: np.ndarray):
        """→ (probe_idx[int64], build_idx[int64]) candidate pair arrays."""
        h = np.ascontiguousarray(probe_hashes, dtype=np.uint64)
        n = len(h)
        state = np.array([0, -1], dtype=np.int64)
        cap = max(1024, n)
        chunks_p, chunks_b = [], []
        while state[0] < n:
            op = np.empty(cap, dtype=np.int64)
            ob = np.empty(cap, dtype=np.int64)
            wrote = _lib.dn_probe_run(
                self._handle, _ptr(h, ctypes.c_uint64), n,
                _ptr(op, ctypes.c_int64), _ptr(ob, ctypes.c_int64), cap,
                _ptr(state, ctypes.c_int64))
            chunks_p.append(op[:wrote])
            chunks_b.append(ob[:wrote])
            if wrote < cap and state[0] >= n:
                break
        if not chunks_p:
            e = np.empty(0, dtype=np.int64)
            return e, e.copy()
        return np.concatenate(chunks_p), np.concatenate(chunks_b)

    def __del__(self):
        if getattr(self, "_handle", None) and _lib is not None:
            _lib.dn_probe_free(self._handle)
            self._handle = None


class BpeVocab:
    """Native BPE vocabulary: byte-sequence → rank lookup table + greedy
    lowest-rank merge encoding (the tokenize hot loop; reference
    capability ``src/daft-functions-tokenize``)."""

    def __init__(self, tokens, ranks):
        """tokens: list[bytes]; ranks: parallel list[int]."""
        lens = np.fromiter((len(t) for t in tokens), dtype=np.int64,
                           count=len(tokens))
        offs = np.zeros(len(tokens) + 1, dtype=np.int64)
        np.cumsum(lens, out=offs[1:])
        data = np.frombuffer(b"".join(tokens), dtype=np.uint8) \
            if tokens else np.empty(0, dtype=np.uint8)
        data = np.ascontiguousarray(data)
        r = np.ascontiguousarray(ranks, dtype=np.int32)
        self._handle = _lib.dn_bpe_build(
            _ptr(offs, ctypes.c_int64),
            _ptr(data, ctypes.c_uint8) if len(data) else _NULL_U8P,
            _ptr(r, ctypes.c_int32), len(tokens))

    def encode(self, piece: bytes):
        """→ int32 ids, or None when some byte sequence has no rank."""
        n = len(piece)
        if n == 0:
            return np.empty(0, dtype=np.int32)
        buf = np.frombuffer(piece, dtype=np.uint8)
        buf = np.ascontiguousarray(buf)
        out = np.empty(n, dtype=np.int32)
        wrote = _lib.dn_bpe_encode(self._handle,
                                   _ptr(buf, ctypes.c_uint8), n,
                                   _ptr(out, ctypes.c_int32))
        if wrote < 0:
            return None
        return out[:wrote]

    def encode_batch(self, pieces):
        """Encode many pieces in ONE native call (amortizes FFI overhead).
        → list of int32 id arrays, or None on an uncovered sequence."""
        if not pieces:
            return []
        lens = np.fromiter((len(p) for p in pieces), dtype=np.int64,
                           count=len(pieces))
        offs = np.zeros(len(pieces) + 1, dtype=np.int64)
        np.cumsum(lens, out=offs[1:])
        data = np.ascontiguousarray(
            np.frombuffer(b"".join(pieces), dtype=np.uint8)) \
            if offs[-1] else np.empty(0, dtype=np.uint8)
        out = np.empty(max(int(offs[-1]), 1), dtype=np.int32)
        counts = np.empty(len(pieces), dtype=np.int64)
        total = _lib.dn_bpe_encode_batch(
            self._handle, _ptr(offs, ctypes.c_int64),
            _ptr(data, ctypes.c_uint8) if len(data) else _NULL_U8P,
            len(pieces), _ptr(out, ctypes.c_int32),
            _ptr(counts, ctypes.c_int64))
        if total < 0:
            return None
        splits = np.cumsum(counts)[:-1]
        return np.split(out[:total], splits)

    def __del__(self):
        if getattr(self, "_handle", None) and _lib is not None:
            _lib.dn_bpe_free(self._handle)
            self._handle = None
