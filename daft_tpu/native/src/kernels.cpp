// daft_tpu native host kernels.
//
// Native (C++) equivalents of the reference engine's Rust data-plane crates
// that have no XLA analogue — row hashing (src/daft-core/src/array/ops/hash.rs,
// src/daft-hash), hash fanout partitioning (src/daft-recordbatch/src/ops/
// partition.rs:53-104), minhash (src/daft-minhash/src/lib.rs), and
// HyperLogLog (src/hyperloglog/src/lib.rs). Algorithms are implemented from
// their public specifications (xxHash64, MurmurHash3 x86_32, HLL++ bias-free
// variant), not translated from the reference sources.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
// All buffers are caller-allocated numpy arrays; sizes are int64_t.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <algorithm>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// xxHash64 (public spec: https://github.com/Cyan4973/xxHash) — scalar
// implementation, used for both fixed-width and variable-width row hashing.
// ---------------------------------------------------------------------------

static const uint64_t P1 = 0x9E3779B185EBCA87ULL;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t P3 = 0x165667B19E3779F9ULL;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t P5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

static inline uint64_t xxh64_round(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl64(acc, 31);
  acc *= P1;
  return acc;
}

static inline uint64_t xxh64_merge_round(uint64_t acc, uint64_t val) {
  val = xxh64_round(0, val);
  acc ^= val;
  acc = acc * P1 + P4;
  return acc;
}

static uint64_t xxh64(const uint8_t* data, int64_t len, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - P1;
    do {
      v1 = xxh64_round(v1, read64(p)); p += 8;
      v2 = xxh64_round(v2, read64(p)); p += 8;
      v3 = xxh64_round(v3, read64(p)); p += 8;
      v4 = xxh64_round(v4, read64(p)); p += 8;
    } while (p <= end - 32);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = xxh64_merge_round(h, v1);
    h = xxh64_merge_round(h, v2);
    h = xxh64_merge_round(h, v3);
    h = xxh64_merge_round(h, v4);
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= xxh64_round(0, read64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

uint64_t dn_xxh64(const uint8_t* data, int64_t len, uint64_t seed) {
  return xxh64(data, len, seed);
}

// Hash each fixed-width element (stride bytes). Invalid rows (valid bitmap
// byte == 0) get NULL_HASH so nulls compare equal in group-by/join keys.
static const uint64_t NULL_HASH = 0x9E3779B97F4A7C15ULL;

void dn_hash_fixed(const uint8_t* data, int64_t n, int64_t stride,
                   const uint8_t* valid, uint64_t seed, uint64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    if (valid && !valid[i]) {
      out[i] = NULL_HASH ^ seed;
    } else {
      out[i] = xxh64(data + i * stride, stride, seed);
    }
  }
}

// Hash variable-width rows given int64 offsets into a flat byte buffer
// (Arrow large_binary layout).
void dn_hash_var(const int64_t* offsets, const uint8_t* data, int64_t n,
                 const uint8_t* valid, uint64_t seed, uint64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    if (valid && !valid[i]) {
      out[i] = NULL_HASH ^ seed;
    } else {
      out[i] = xxh64(data + offsets[i], offsets[i + 1] - offsets[i], seed);
    }
  }
}

// Combine a row-hash column with a per-row seed column (multi-key hashing):
// splitmix64 finalizer over (h ^ seed), matching the Python fallback.
void dn_hash_combine(const uint64_t* h, const uint64_t* seed, int64_t n,
                     uint64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t x = h[i] ^ seed[i];
    x += 0x9E3779B97F4A7C15ULL;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
    out[i] = x ^ (x >> 31);
  }
}

// MurmurHash3 x86_32 (public spec) — parity with src/daft-hash's murmur3.
uint32_t dn_murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  const uint32_t c1 = 0xcc9e2d51, c2 = 0x1b873593;
  uint32_t h = seed;
  int64_t nblocks = len / 4;
  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k = read32(data + i * 4);
    k *= c1; k = (k << 15) | (k >> 17); k *= c2;
    h ^= k; h = (h << 13) | (h >> 19); h = h * 5 + 0xe6546b64;
  }
  uint32_t k = 0;
  const uint8_t* tail = data + nblocks * 4;
  switch (len & 3) {
    case 3: k ^= (uint32_t)tail[2] << 16; [[fallthrough]];
    case 2: k ^= (uint32_t)tail[1] << 8;  [[fallthrough]];
    case 1: k ^= tail[0];
      k *= c1; k = (k << 15) | (k >> 17); k *= c2; h ^= k;
  }
  h ^= (uint32_t)len;
  h ^= h >> 16; h *= 0x85ebca6b; h ^= h >> 13; h *= 0xc2b2ae35; h ^= h >> 16;
  return h;
}

// ---------------------------------------------------------------------------
// Hash fanout partitioning: pid = h % nparts, then counting-sort row indices
// into per-partition contiguous runs (one pass, no per-partition scans).
// counts: [nparts], indices: [n] (gather list; partition p's rows live at
// indices[starts[p] .. starts[p]+counts[p])).
// ---------------------------------------------------------------------------

void dn_fanout_hash(const uint64_t* h, int64_t n, int64_t nparts,
                    int64_t* counts, int64_t* indices, int64_t* pid_out) {
  std::memset(counts, 0, sizeof(int64_t) * nparts);
  for (int64_t i = 0; i < n; i++) {
    int64_t p = (int64_t)(h[i] % (uint64_t)nparts);
    if (pid_out) pid_out[i] = p;
    counts[p]++;
  }
  std::vector<int64_t> cursor(nparts, 0);
  int64_t acc = 0;
  for (int64_t p = 0; p < nparts; p++) { cursor[p] = acc; acc += counts[p]; }
  for (int64_t i = 0; i < n; i++) {
    int64_t p = (int64_t)(h[i] % (uint64_t)nparts);
    indices[cursor[p]++] = i;
  }
}

// Same counting sort for precomputed partition ids (range/random fanout).
void dn_fanout_pid(const int64_t* pid, int64_t n, int64_t nparts,
                   int64_t* counts, int64_t* indices) {
  std::memset(counts, 0, sizeof(int64_t) * nparts);
  for (int64_t i = 0; i < n; i++) counts[pid[i]]++;
  std::vector<int64_t> cursor(nparts, 0);
  int64_t acc = 0;
  for (int64_t p = 0; p < nparts; p++) { cursor[p] = acc; acc += counts[p]; }
  for (int64_t i = 0; i < n; i++) indices[cursor[pid[i]]++] = i;
}

// ---------------------------------------------------------------------------
// MinHash (near-duplicate detection). Word-level shingles of `ngram_size`
// tokens; k permutations h_j = (a_j * x + b_j) mod p over xxh64 token-window
// hashes; output the per-permutation minimum as u32 (reference signature:
// src/daft-minhash/src/lib.rs — same contract, independent implementation).
// ---------------------------------------------------------------------------

static const uint64_t MERSENNE_P = (1ULL << 61) - 1;

static inline uint64_t mulmod61(uint64_t a, uint64_t b) {
  __uint128_t r = (__uint128_t)a * b;
  uint64_t lo = (uint64_t)(r & MERSENNE_P);
  uint64_t hi = (uint64_t)(r >> 61);
  uint64_t s = lo + hi;
  if (s >= MERSENNE_P) s -= MERSENNE_P;
  return s;
}

// xorshift generator for permutation coefficients (deterministic in seed)
static inline uint64_t next_rand(uint64_t* state) {
  uint64_t x = *state;
  x ^= x << 13; x ^= x >> 7; x ^= x << 17;
  *state = x;
  return x;
}

void dn_minhash(const int64_t* offsets, const uint8_t* data, int64_t n,
                const uint8_t* valid, int32_t num_hashes, int32_t ngram_size,
                uint64_t seed, uint32_t* out /* [n * num_hashes] */) {
  std::vector<uint64_t> perm_a(num_hashes), perm_b(num_hashes);
  uint64_t st = seed ? seed : 1;
  for (int32_t j = 0; j < num_hashes; j++) {
    perm_a[j] = next_rand(&st) % (MERSENNE_P - 1) + 1;
    perm_b[j] = next_rand(&st) % MERSENNE_P;
  }
  std::vector<int64_t> word_starts;
  std::vector<int64_t> word_ends;
  for (int64_t i = 0; i < n; i++) {
    uint32_t* row = out + i * num_hashes;
    if (valid && !valid[i]) {
      std::fill(row, row + num_hashes, 0xFFFFFFFFu);
      continue;
    }
    const uint8_t* s = data + offsets[i];
    int64_t len = offsets[i + 1] - offsets[i];
    // split on ASCII whitespace
    word_starts.clear(); word_ends.clear();
    int64_t w = -1;
    for (int64_t k = 0; k < len; k++) {
      bool ws = s[k] == ' ' || s[k] == '\t' || s[k] == '\n' || s[k] == '\r';
      if (!ws && w < 0) w = k;
      if (ws && w >= 0) { word_starts.push_back(w); word_ends.push_back(k); w = -1; }
    }
    if (w >= 0) { word_starts.push_back(w); word_ends.push_back(len); }
    int64_t nwords = (int64_t)word_starts.size();
    std::fill(row, row + num_hashes, 0xFFFFFFFFu);
    if (nwords == 0) continue;
    int64_t nshingles = std::max<int64_t>(1, nwords - ngram_size + 1);
    for (int64_t sh = 0; sh < nshingles; sh++) {
      int64_t last = std::min<int64_t>(sh + ngram_size, nwords) - 1;
      // hash the byte span covering the shingle's words (incl. separators)
      uint64_t hv = xxh64(s + word_starts[sh],
                          word_ends[last] - word_starts[sh], 42);
      hv &= MERSENNE_P;  // into field
      for (int32_t j = 0; j < num_hashes; j++) {
        uint64_t ph = mulmod61(perm_a[j], hv) + perm_b[j];
        if (ph >= MERSENNE_P) ph -= MERSENNE_P;
        uint32_t v = (uint32_t)(ph & 0xFFFFFFFFu);
        if (v < row[j]) row[j] = v;
      }
    }
  }
}

// ---------------------------------------------------------------------------
// HyperLogLog (dense, 2^p registers; standard HLL estimator with small-range
// linear counting correction — same contract as src/hyperloglog).
// ---------------------------------------------------------------------------

void dn_hll_add(uint8_t* registers, int32_t p, const uint64_t* hashes,
                int64_t n) {
  int64_t m = 1LL << p;
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = hashes[i];
    uint64_t idx = h >> (64 - p);
    uint64_t rest = h << p;
    uint8_t rho = rest == 0 ? (uint8_t)(64 - p + 1)
                            : (uint8_t)(__builtin_clzll(rest) + 1);
    if (rho > registers[idx]) registers[idx] = rho;
    (void)m;
  }
}

void dn_hll_merge(uint8_t* dst, const uint8_t* src, int64_t m) {
  for (int64_t i = 0; i < m; i++) dst[i] = std::max(dst[i], src[i]);
}

double dn_hll_estimate(const uint8_t* registers, int32_t p) {
  int64_t m = 1LL << p;
  double alpha;
  switch (m) {
    case 16: alpha = 0.673; break;
    case 32: alpha = 0.697; break;
    case 64: alpha = 0.709; break;
    default: alpha = 0.7213 / (1.0 + 1.079 / (double)m);
  }
  double sum = 0.0;
  int64_t zeros = 0;
  for (int64_t i = 0; i < m; i++) {
    sum += std::ldexp(1.0, -registers[i]);
    if (registers[i] == 0) zeros++;
  }
  double e = alpha * m * m / sum;
  if (e <= 2.5 * m && zeros > 0) {
    e = m * std::log((double)m / zeros);  // linear counting
  }
  return e;
}

// ---------------------------------------------------------------------------
// Hash-join probe table: build u64-hash → row-chain map over the build side,
// then stream probe hashes to emit (probe_idx, build_idx) candidate pairs.
// Callers verify key equality on the emitted pairs (hash collisions), the
// same split as the reference's probeable/probe_table.rs contract.
// ---------------------------------------------------------------------------

struct ProbeTable {
  std::vector<int64_t> heads;   // bucket -> first row (or -1)
  std::vector<int64_t> next;    // row -> next row in chain (or -1)
  std::vector<uint64_t> hashes; // build-side row hashes
  uint64_t mask;
};

void* dn_probe_build(const uint64_t* h, int64_t n) {
  auto* t = new ProbeTable();
  int64_t cap = 16;
  while (cap < n * 2) cap <<= 1;
  t->mask = (uint64_t)(cap - 1);
  t->heads.assign(cap, -1);
  t->next.assign(n, -1);
  t->hashes.assign(h, h + n);
  for (int64_t i = 0; i < n; i++) {
    uint64_t b = h[i] & t->mask;
    t->next[i] = t->heads[b];
    t->heads[b] = i;
  }
  return t;
}

// Emits up to cap pairs; returns number of pairs written. `state` carries the
// resume position ({probe_idx, chain_pos}) so callers can loop on overflow.
int64_t dn_probe_run(void* table, const uint64_t* probe_h, int64_t n_probe,
                     int64_t* out_probe, int64_t* out_build, int64_t cap,
                     int64_t* state /* [2] */) {
  auto* t = (ProbeTable*)table;
  int64_t written = 0;
  int64_t i = state[0];
  int64_t chain = state[1];
  for (; i < n_probe; i++) {
    uint64_t h = probe_h[i];
    int64_t j = chain >= 0 ? chain : t->heads[h & t->mask];
    chain = -1;
    while (j >= 0) {
      if (t->hashes[j] == h) {
        if (written == cap) { state[0] = i; state[1] = j; return written; }
        out_probe[written] = i;
        out_build[written] = j;
        written++;
      }
      j = t->next[j];
    }
  }
  state[0] = n_probe;
  state[1] = -1;
  return written;
}

void dn_probe_free(void* table) { delete (ProbeTable*)table; }

// ---------------------------------------------------------------------------
// BPE vocabulary + greedy lowest-rank merge encoding (the tokenize hot loop;
// reference capability: src/daft-functions-tokenize over tiktoken). The
// vocabulary maps byte sequences → ranks; encoding repeatedly merges the
// adjacent pair with the lowest rank until no merge applies.

struct BpeVocab {
  // flat storage of tokens, looked up through an open-addressing table of
  // (hash, offset, len, rank)
  std::vector<uint8_t> bytes;
  std::vector<int64_t> offs;   // n+1 offsets into bytes
  std::vector<int32_t> ranks;  // rank per token
  std::vector<int64_t> slots;  // hash table: index into offs/ranks, -1 empty
  uint64_t mask = 0;

  int32_t lookup(const uint8_t* p, int64_t len) const {
    uint64_t h = xxh64(p, len, 0);
    uint64_t i = h & mask;
    while (true) {
      int64_t s = slots[i];
      if (s < 0) return -1;
      int64_t tl = offs[s + 1] - offs[s];
      if (tl == len && std::memcmp(&bytes[offs[s]], p, len) == 0)
        return ranks[s];
      i = (i + 1) & mask;
    }
  }
};

void* dn_bpe_build(const int64_t* offsets, const uint8_t* data,
                   const int32_t* ranks, int64_t n) {
  auto* v = new BpeVocab();
  int64_t total = offsets[n];
  v->bytes.assign(data, data + total);
  v->offs.assign(offsets, offsets + n + 1);
  v->ranks.assign(ranks, ranks + n);
  int64_t cap = 16;
  while (cap < n * 2) cap <<= 1;
  v->mask = (uint64_t)cap - 1;
  v->slots.assign(cap, -1);
  for (int64_t s = 0; s < n; s++) {
    const uint8_t* p = &v->bytes[v->offs[s]];
    int64_t len = v->offs[s + 1] - v->offs[s];
    uint64_t i = xxh64(p, len, 0) & v->mask;
    while (v->slots[i] >= 0) i = (i + 1) & v->mask;
    v->slots[i] = s;
  }
  return v;
}

static int64_t bpe_encode_one(const BpeVocab* v, const uint8_t* piece,
                              int64_t len, int32_t* out) {
  if (len == 0) return 0;
  int32_t whole = v->lookup(piece, len);
  if (whole >= 0) { out[0] = whole; return 1; }
  // parts as (start, len) plus the rank of each adjacent pair; a merge
  // only invalidates the two pair-ranks touching the merge point, so each
  // iteration costs one O(n) min-scan + two lookups (the tiktoken
  // recipe), not a full pair-rank recomputation
  std::vector<int64_t> starts(len), lens(len, 1);
  std::vector<int32_t> pair_rank(len > 1 ? len - 1 : 0);
  for (int64_t i = 0; i < len; i++) starts[i] = i;
  int64_t nparts = len;
  for (int64_t i = 0; i + 1 < nparts; i++)
    pair_rank[i] = v->lookup(piece + starts[i], 2);
  while (nparts > 1) {
    int32_t best_rank = -1;
    int64_t best_i = -1;
    for (int64_t i = 0; i + 1 < nparts; i++) {
      int32_t r = pair_rank[i];
      if (r >= 0 && (best_rank < 0 || r < best_rank)) {
        best_rank = r;
        best_i = i;
      }
    }
    if (best_i < 0) break;
    lens[best_i] += lens[best_i + 1];
    for (int64_t i = best_i + 1; i + 1 < nparts; i++) {
      starts[i] = starts[i + 1];
      lens[i] = lens[i + 1];
      if (i + 2 < nparts) pair_rank[i] = pair_rank[i + 1];
    }
    nparts--;
    if (best_i > 0)
      pair_rank[best_i - 1] = v->lookup(
          piece + starts[best_i - 1], lens[best_i - 1] + lens[best_i]);
    if (best_i + 1 < nparts)
      pair_rank[best_i] = v->lookup(
          piece + starts[best_i], lens[best_i] + lens[best_i + 1]);
  }
  for (int64_t i = 0; i < nparts; i++) {
    int32_t r = v->lookup(piece + starts[i], lens[i]);
    if (r < 0) return -1;
    out[i] = r;
  }
  return nparts;
}

// Encode one pretokenized piece. Returns the number of ids written (≤ len),
// or -1 if some byte sequence has no rank (vocab lacks single-byte tokens).
int64_t dn_bpe_encode(void* vocab, const uint8_t* piece, int64_t len,
                      int32_t* out) {
  return bpe_encode_one((BpeVocab*)vocab, piece, len, out);
}

// Encode a batch of pretokenized pieces in one call (amortizes the FFI
// round-trip — the per-piece path loses to call overhead on short pieces).
// out must hold piece_offs[n_pieces] ids; out_counts[i] receives piece i's
// id count. Returns total ids written, or -1 on an uncovered sequence.
int64_t dn_bpe_encode_batch(void* vocab, const int64_t* piece_offs,
                            const uint8_t* data, int64_t n_pieces,
                            int32_t* out, int64_t* out_counts) {
  auto* v = (BpeVocab*)vocab;
  int64_t pos = 0;
  for (int64_t p = 0; p < n_pieces; p++) {
    int64_t wrote = bpe_encode_one(v, data + piece_offs[p],
                                   piece_offs[p + 1] - piece_offs[p],
                                   out + pos);
    if (wrote < 0) return -1;
    out_counts[p] = wrote;
    pos += wrote;
  }
  return pos;
}

void dn_bpe_free(void* vocab) { delete (BpeVocab*)vocab; }

}  // extern "C"
