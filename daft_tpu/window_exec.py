"""Window function execution.

Reference: the four window sinks of ``src/daft-local-execution/src/sinks/
window_*.rs`` + running-state machines (``ops/window_states/``). Here:
per-partition-batch evaluation — group rows by the window's partition keys,
order within groups, compute rank family / lag / lead / aggregate values
(full-frame, running, or explicit rows frame), scatter back to row order.
Vectorized with numpy over group segments.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .datatype import DataType
from .expressions import Expression, col
from .schema import Field, Schema
from .series import Series


def window_field(e: Expression, schema: Schema) -> Field:
    out_name = e.name()
    w = e._unalias()
    assert w.op == "window"
    base = w.args[0]._unalias()
    if base.op in ("winfn.row_number", "winfn.rank", "winfn.dense_rank"):
        return Field(out_name if e.op == "alias" else base.op[6:],
                     DataType.uint64())
    f = base.to_field(schema)
    return Field(out_name if e.op == "alias" else f.name, f.dtype)


def _expr_of(e: Expression) -> Expression:
    """The window node's inner computation (unaliased)."""
    return e.args[0] if e.op == "window" else e


def run_window(rb, node):
    """Evaluate node.window_exprs over one (already partition-clustered)
    RecordBatch; appends output columns in row order."""
    n = len(rb)
    if n == 0:
        from .recordbatch import RecordBatch
        extra = [Series.empty(e.name(), window_field(e, rb.schema).dtype)
                 for e in node.window_exprs]
        return RecordBatch.from_series(rb.columns() + extra) if rb.columns() \
            else RecordBatch.empty(node.schema())
    schema = rb.schema
    # sort rows by (partition keys, order keys) once; remember inverse perm
    part_keys = list(node.partition_by)
    order_keys = list(node.order_by)
    sort_keys = part_keys + order_keys
    if sort_keys:
        desc = [False] * len(part_keys) + list(node.descending)
        nf = [False] * len(part_keys) + list(node.nulls_first)
        perm = rb.argsort(sort_keys, desc, nf)
    else:
        perm = np.arange(n)
    # pre-clustered input (window after an engine sort on the same keys —
    # the TPC-DS q47/q63/q89 shape): the permutation is the identity, so
    # skip the full-batch Arrow take AND the inverse-scatter on every
    # output column
    if np.array_equal(perm, np.arange(n)):
        inv = None
        sorted_rb = rb
    else:
        inv = np.empty(n, dtype=np.int64)
        inv[perm] = np.arange(n)
        sorted_rb = rb.take(perm)

    # segment ids over partition keys in sorted order
    if part_keys:
        keys = [sorted_rb.eval_expression(e) for e in part_keys]
        seg = _segment_ids(keys)
    else:
        seg = np.zeros(n, dtype=np.int64)
    seg_starts = np.flatnonzero(np.diff(np.concatenate([[-1], seg])))
    starts_per_row = seg_starts[seg]  # first row index of each row's group

    # order-key change flags (peer-run boundaries) in sorted order
    order_vals = None
    if order_keys:
        okeys = [sorted_rb.eval_expression(e) for e in order_keys]
        oseg = _segment_ids(okeys)
        order_change = np.zeros(n, dtype=bool)
        if n:
            order_change[0] = True
            order_change[1:] = np.diff(oseg) != 0
        if not okeys[0].is_pyobject():
            ov = okeys[0].to_numpy()
            if ov.dtype != object and ov.dtype.kind in "iuf":
                order_vals = ov.astype(np.float64)
    else:
        order_change = np.zeros(n, dtype=bool)

    out_cols: List[Series] = []
    for we in node.window_exprs:
        spec_expr = we._unalias()
        assert spec_expr.op == "window"
        inner = spec_expr.args[0]._unalias()
        name = we.name()
        frame = node.frame
        has_order = bool(order_keys)
        out = _eval_window_fn(inner, sorted_rb, seg, starts_per_row, n,
                              has_order, frame, name, order_change, order_vals)
        out_cols.append((out if inv is None else out.take(inv)).rename(name))
    from .recordbatch import RecordBatch
    return RecordBatch.from_series(rb.columns() + out_cols)


def _segment_ids(keys: List[Series]) -> np.ndarray:
    n = len(keys[0])
    change = np.zeros(n, dtype=bool)
    change[0] = True
    for k in keys:
        vals = k.to_numpy()
        if vals.dtype == object:
            cur = np.array([v != w for v, w in zip(vals[1:], vals[:-1])],
                           dtype=bool)
        else:
            a, b = vals[1:], vals[:-1]
            with np.errstate(invalid="ignore"):
                cur = a != b
                isnan = (a != a) & (b != b)
                cur = np.where(isnan, False, cur)
        change[1:] |= cur
        nulls = np.asarray(k.is_null().to_numpy())
        change[1:] |= nulls[1:] != nulls[:-1]
    return np.cumsum(change) - 1


def _eval_window_fn(inner: Expression, sorted_rb, seg, starts_per_row, n,
                    has_order, frame, name, order_change,
                    order_vals=None) -> Series:
    import pyarrow as pa
    pos_in_group = np.arange(n) - starts_per_row

    if inner.op == "winfn.row_number":
        return Series.from_arrow(pa.array((pos_in_group + 1).astype(np.uint64)),
                                 name)
    if inner.op in ("winfn.rank", "winfn.dense_rank"):
        new_run = order_change | (pos_in_group == 0)
        if inner.op == "winfn.rank":
            # rank = 1-based position of the first row of the peer run
            rank = _segment_carry(pos_in_group + 1, new_run)
            return Series.from_arrow(pa.array(rank.astype(np.uint64)), name)
        flags = new_run.astype(np.int64)
        cums = np.cumsum(flags)
        seg_firsts = np.flatnonzero(pos_in_group == 0)
        base_vals = cums[seg_firsts] - 1
        dense = cums - base_vals[seg]
        return Series.from_arrow(pa.array(dense.astype(np.uint64)), name)
    if inner.op in ("winfn.lag", "winfn.lead"):
        offset = inner.params[0]
        child = sorted_rb.eval_expression(inner.args[0])
        default = None
        if len(inner.args) > 1:
            default = sorted_rb.eval_expression(inner.args[1])
        shift = offset if inner.op == "winfn.lag" else -offset
        idx = np.arange(n) - shift
        valid = (idx >= 0) & (idx < n)
        if len(seg):
            valid &= np.where((idx >= 0) & (idx < n),
                              seg[np.clip(idx, 0, n - 1)] == seg, False)
        import pyarrow as pa2
        ia = pa2.array(np.clip(idx, 0, max(n - 1, 0)), mask=~valid)
        out = child.to_arrow().take(ia) if not child.is_pyobject() else None
        if out is None:
            vals = child.to_pylist()
            out_l = [vals[i] if v else None for i, v in zip(np.clip(idx, 0, n - 1), valid)]
            s = Series.from_pylist(out_l, name, dtype=child.datatype())
        else:
            s = Series(name, child.datatype(), arrow=out)
        if default is not None:
            fill = default.broadcast(n) if len(default) == 1 else default
            import pyarrow.compute as pc
            s = Series(name, s.datatype(), arrow=pc.if_else(
                pa.array(valid), s.to_arrow(),
                fill.cast(s.datatype()).to_arrow()))
        return s
    if inner.op.startswith("agg."):
        return _eval_window_agg(inner, sorted_rb, seg, starts_per_row, n,
                                has_order, frame, name, order_vals)
    raise NotImplementedError(f"window function {inner.op}")


def _segment_carry(values: np.ndarray, flags: np.ndarray) -> np.ndarray:
    """For each row, the value at the last index where flags was True."""
    idx = np.where(flags, np.arange(len(values)), 0)
    idx = np.maximum.accumulate(idx)
    return values[idx]


def _eval_window_agg(inner, sorted_rb, seg, starts_per_row, n, has_order,
                     frame, name, order_vals=None) -> Series:
    import pyarrow as pa
    op = inner.op[4:]
    child = inner.args[0] if inner.args else None
    vals_s = sorted_rb.eval_expression(child) if child is not None else None
    out_dtype = inner.to_field(sorted_rb.schema).dtype

    if vals_s is not None and not vals_s.is_pyobject():
        v = vals_s.to_numpy()
        valid = np.asarray(vals_s.not_null().to_numpy())
        if v.dtype == object or v.dtype.kind in "mM":
            v = None
    else:
        v = None
    if v is None:
        # generic python fallback per group
        return _py_window_agg(inner, sorted_rb, seg, n, has_order, frame, name,
                              out_dtype, vals_s)

    vf = np.where(valid, v, 0).astype(np.float64)
    ones = valid.astype(np.float64)
    nseg = int(seg[-1]) + 1 if n else 0

    if frame is not None:
        return _frame_agg(op, vf, v, valid, seg, starts_per_row, n,
                          frame, name, out_dtype, order_vals)

    if has_order and op in ("sum", "mean", "count", "min", "max"):
        # running aggregate (default SQL frame: unbounded preceding→current)
        csum = _seg_cumsum(vf, seg)
        ccnt = _seg_cumsum(ones, seg)
        if op == "count":
            out = ccnt
        elif op == "sum":
            out = csum
        elif op == "mean":
            with np.errstate(invalid="ignore"):
                out = csum / np.where(ccnt == 0, np.nan, ccnt)
        elif op in ("min", "max"):
            x = np.where(valid, v.astype(np.float64),
                         np.inf if op == "min" else -np.inf)
            out = _seg_cummin(x, seg) if op == "min" else -_seg_cummin(-x, seg)
            out = np.where(ccnt > 0, out, np.nan)
        mask = (ccnt == 0) if op != "count" else np.zeros(n, dtype=bool)
        return _np_to_series(out, mask, name, out_dtype)

    # full-partition aggregate
    sums = np.bincount(seg, weights=vf, minlength=nseg)
    cnts = np.bincount(seg, weights=ones, minlength=nseg)
    if op == "count":
        out = cnts[seg]
        return _np_to_series(out, np.zeros(n, dtype=bool), name, out_dtype)
    if op == "sum":
        out = sums[seg]
        return _np_to_series(out, cnts[seg] == 0, name, out_dtype)
    if op == "mean":
        with np.errstate(invalid="ignore"):
            m = sums / np.where(cnts == 0, np.nan, cnts)
        return _np_to_series(m[seg], cnts[seg] == 0, name, out_dtype)
    if op in ("min", "max"):
        x = np.where(valid, v.astype(np.float64),
                     np.inf if op == "min" else -np.inf)
        red = np.full(nseg, np.inf if op == "min" else -np.inf)
        np.minimum.at(red, seg, x) if op == "min" else \
            np.maximum.at(red, seg, x)
        return _np_to_series(red[seg], cnts[seg] == 0, name, out_dtype)
    if op in ("stddev", "var"):
        s2 = np.bincount(seg, weights=vf * vf, minlength=nseg)
        with np.errstate(invalid="ignore"):
            mean = sums / np.where(cnts == 0, np.nan, cnts)
            var = s2 / np.where(cnts == 0, np.nan, cnts) - mean * mean
            var = np.maximum(var, 0)
            out = np.sqrt(var) if op == "stddev" else var
        return _np_to_series(out[seg], cnts[seg] == 0, name, out_dtype)
    return _py_window_agg(inner, sorted_rb, seg, n, has_order, frame, name,
                          out_dtype, vals_s)


def _frame_agg(op, vf, v, valid, seg, starts_per_row, n, frame, name,
               out_dtype, order_vals=None):
    kind, start, end = frame[0], frame[1], frame[2]
    min_periods = frame[3] if len(frame) > 3 else 1
    # end index (exclusive) of each row's segment
    last = np.flatnonzero(np.diff(np.concatenate([seg, [-2]])))
    seg_end_per_seg = last + 1
    seg_ends = seg_end_per_seg[seg]
    i = np.arange(n)
    if kind == "rows":
        lo = starts_per_row if start == "unbounded_preceding" else \
            np.clip(i + int(start), starts_per_row, seg_ends)
        hi = seg_ends if end == "unbounded_following" else \
            np.clip(i + int(end) + 1, starts_per_row, seg_ends)
    else:  # range frame over the first (numeric) order key
        if order_vals is None:
            raise NotImplementedError(
                "range_between requires one numeric order_by key")
        lo = np.empty(n, dtype=np.int64)
        hi = np.empty(n, dtype=np.int64)
        for s_start in np.flatnonzero(
                np.diff(np.concatenate([[-1], seg]))):
            s_end = seg_end_per_seg[seg[s_start]]
            block = order_vals[s_start:s_end]
            cur = block
            if start == "unbounded_preceding":
                lo[s_start:s_end] = s_start
            else:
                lo[s_start:s_end] = s_start + np.searchsorted(
                    block, cur + float(start), side="left")
            if end == "unbounded_following":
                hi[s_start:s_end] = s_end
            else:
                hi[s_start:s_end] = s_start + np.searchsorted(
                    block, cur + float(end), side="right")
    hi = np.maximum(hi, lo)
    cs = np.concatenate([[0.0], np.cumsum(vf)])
    cn = np.concatenate([[0.0], np.cumsum(valid.astype(np.float64))])
    s = cs[hi] - cs[lo]
    c = cn[hi] - cn[lo]
    null_out = c < max(min_periods, 1)
    if op == "count":
        return _np_to_series(c, null_out & (min_periods > 1), name, out_dtype)
    if op == "sum":
        return _np_to_series(s, null_out, name, out_dtype)
    if op == "mean":
        with np.errstate(invalid="ignore"):
            return _np_to_series(s / np.where(c == 0, np.nan, c), null_out,
                                 name, out_dtype)
    if op in ("min", "max"):
        # O(n·w) fallback for min/max frames
        x = np.where(valid, v.astype(np.float64),
                     np.inf if op == "min" else -np.inf)
        out = np.empty(n)
        for j in range(n):
            w = x[lo[j]:hi[j]]
            out[j] = (w.min() if op == "min" else w.max()) if len(w) else np.nan
        return _np_to_series(out, null_out, name, out_dtype)
    raise NotImplementedError(f"frame window agg {op}")


def _py_window_agg(inner, sorted_rb, seg, n, has_order, frame, name,
                   out_dtype, vals_s):
    from .aggs import _global_one
    out = []
    nseg = int(seg[-1]) + 1 if n else 0
    op = inner.op[4:]
    for g in range(nseg):
        idx = np.flatnonzero(seg == g)
        sub = vals_s.take(idx) if vals_s is not None else None
        r = _global_one(op, sub, name, inner.params).to_pylist()[0]
        out.extend([r] * len(idx))
    return Series.from_pylist(out, name, dtype=out_dtype)


def _seg_cumsum(x: np.ndarray, seg: np.ndarray) -> np.ndarray:
    cs = np.cumsum(x)
    seg_firsts = np.flatnonzero(np.diff(np.concatenate([[-1], seg])))
    base = np.concatenate([[0.0], cs[seg_firsts[1:] - 1]]) if len(seg_firsts) \
        else np.zeros(0)
    return cs - base[seg]


def _seg_cummin(x: np.ndarray, seg: np.ndarray) -> np.ndarray:
    out = np.empty_like(x)
    seg_firsts = np.flatnonzero(np.diff(np.concatenate([[-1], seg])))
    for si, start in enumerate(seg_firsts):
        end = seg_firsts[si + 1] if si + 1 < len(seg_firsts) else len(x)
        out[start:end] = np.minimum.accumulate(x[start:end])
    return out


def _np_to_series(out: np.ndarray, null_mask: np.ndarray, name: str,
                  dtype: DataType) -> Series:
    import pyarrow as pa
    arr = pa.array(out, mask=null_mask | np.isnan(out)
                   if out.dtype.kind == "f" and not dtype.is_floating()
                   else null_mask)
    s = Series.from_arrow(arr, name)
    return s.cast(dtype)
