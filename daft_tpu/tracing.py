"""Query-wide tracing plane: distributed span propagation + exports.

One query = one trace. Explicit span context (trace id, span id, parent
id) threads through every layer that already has stats hooks:

- the serving scheduler (queue-wait + run spans, cancellation events),
- the planner (optimize / translate / fingerprint-cache outcome),
- the device runtime (one span per dispatch, annotated with the MFU
  ledger's strategy/bytes/flops — the roofline story on the timeline),
- pipeline stages and scan-prefetch producers (riding the existing
  thread-attribution machinery in ``observability``),
- the distributed tier: span context travels over the HTTP/Flight
  shuffle wire as headers and over the remote-worker RPC; workers emit
  child spans for task run / fetch / retry / lineage-recompute /
  speculation and ship them back with task results; the driver merges
  them — with per-worker clock-offset correction — into ONE query trace.

Exports: Chrome trace JSON (perfetto-loadable) per query
(``DAFT_TPU_TRACE_DIR``), OTLP spans (``DAFT_TPU_OTLP_ENDPOINT``,
``/v1/traces`` beside the metrics export), a Prometheus text-format
``/metrics`` scrape on the dashboard, and a bounded flight recorder
(``DAFT_TPU_QUERY_LOG`` JSONL with size-capped rotation) served at
``/api/history``.

Design contracts:

- **near-free when off** — span creation guards on the thread's current
  span context (one ``getattr``); no dicts, no ids, no timestamps are
  built for untraced queries. The per-query enable decision
  (``DAFT_TPU_TRACE`` × ``DAFT_TPU_TRACE_SAMPLE``) happens once at
  trace creation.
- **deterministic under chaos** — span ids are minted by hashing the
  planner's stable identities (``Stage.task_key`` fault keys, operator
  names, attempt numbers), never RNG, so a seeded
  ``DAFT_TPU_CHAOS_SERIALIZE=1`` run replays bit-identical span ids.
- **bounded** — ``DAFT_TPU_TRACE_MAX_SPANS`` caps the per-query buffer
  (drops counted), the recorder registry is size-capped, and the flight
  recorder rotates at ``DAFT_TPU_QUERY_LOG_BYTES``.
"""

from __future__ import annotations

import contextlib
import hashlib
import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------- ids

#: spans whose trace buffer is full are counted, never stored; the
#: registry holds at most this many ACTIVE (unexported) recorders —
#: an abandoned trace must not leak its spans forever
_MAX_ACTIVE_RECORDERS = 64

_WIRE_TRACE_HEADER = "X-Daft-Trace-Id"
_WIRE_PARENT_HEADER = "X-Daft-Parent-Span"


def span_id_from(key: str) -> str:
    """16-hex span id from a stable key. Pure function of the key — the
    same planner-minted identity yields the same id run after run, which
    is the chaos-replay contract for traces."""
    return hashlib.sha256(b"daft-span\x1f"
                          + key.encode()).hexdigest()[:16]


def _hash01(key: str) -> float:
    h = hashlib.sha256(b"daft-trace\x1f" + key.encode()).digest()
    return int.from_bytes(h[:8], "big") / 2 ** 64


def _now_us() -> int:
    return int(time.time() * 1e6)


# ----------------------------------------------------------- recorder


class SpanRecorder:
    """One query's span buffer. Bounded; thread-safe; ids deterministic."""

    def __init__(self, trace_id: str, max_spans: Optional[int] = None):
        if max_spans is None:
            from .analysis import knobs
            max_spans = knobs.env_int("DAFT_TPU_TRACE_MAX_SPANS")
        self.trace_id = trace_id
        self.max_spans = max(int(max_spans), 1)
        self._lock = threading.Lock()
        self._spans: List[dict] = []
        self.dropped = 0
        self._key_seq: Dict[str, int] = {}
        self.clock_offsets_us: Dict[str, int] = {}
        self.root_id = span_id_from("query")
        self._root_t0 = _now_us()
        self._finished = False
        self.exported = False
        self.status = "ok"

    # -- id minting ---------------------------------------------------
    def unique_key(self, key: str) -> str:
        """``key``, suffixed ``~N`` on repeats — a recomputed map task
        reuses its stable fault key; its spans must still be distinct.
        The counter is deterministic whenever execution order is
        (which ``DAFT_TPU_CHAOS_SERIALIZE=1`` guarantees)."""
        with self._lock:
            n = self._key_seq.get(key, 0)
            self._key_seq[key] = n + 1
        return key if n == 0 else f"{key}~{n}"

    def unique_span_id(self, key: str) -> str:
        return span_id_from(self.unique_key(key))

    # -- recording ----------------------------------------------------
    def add(self, name: str, span_id: str, parent_id: Optional[str],
            ts_us: int, dur_us: int, attrs: Optional[dict] = None,
            lane: str = "driver", status: str = "ok") -> None:
        span = {"name": name, "span_id": span_id,
                "parent_id": parent_id or self.root_id,
                "ts_us": int(ts_us), "dur_us": max(int(dur_us), 0),
                "lane": lane}
        if attrs:
            span["attrs"] = attrs
        if status != "ok":
            span["status"] = status
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
                return
            self._spans.append(span)

    def add_remote(self, spans: List[dict], offset_us: int,
                   worker: str) -> None:
        """Merge spans shipped back from another process, correcting
        their wall clock by the measured offset."""
        with self._lock:
            self.clock_offsets_us[worker] = int(offset_us)
        for s in spans:
            try:
                self.add(s["name"], s["span_id"], s.get("parent_id"),
                         int(s["ts_us"]) + int(offset_us), s["dur_us"],
                         attrs=s.get("attrs"),
                         lane=s.get("lane") or f"worker:{worker}",
                         status=s.get("status", "ok"))
            except (KeyError, TypeError, ValueError):
                self.dropped += 1

    def finish(self, status: Optional[str] = None) -> None:
        """Close the root span (idempotent). ``None`` keeps whatever
        status was pre-set on the recorder (a failed query marks it
        ``error`` before the export path finishes the root)."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
        if status is not None:
            self.status = status
        self.add("query", self.root_id, None, self._root_t0,
                 _now_us() - self._root_t0, lane="driver",
                 status=self.status)

    def spans(self) -> List[dict]:
        with self._lock:
            return list(self._spans)

    def drain(self) -> List[dict]:
        """Remove and return the buffered spans (ship-back path: each
        remote task response carries the spans recorded so far, so
        concurrent tasks of one trace never double-ship)."""
        with self._lock:
            out = self._spans
            self._spans = []
            return out

    def span_ids(self) -> set:
        with self._lock:
            return {s["span_id"] for s in self._spans}

    def summary(self) -> dict:
        with self._lock:
            n = len(self._spans)
            offsets = dict(self.clock_offsets_us)
        out = {"trace_id": self.trace_id, "spans": n,
               "dropped": self.dropped}
        if offsets:
            out["clock_offsets_us"] = offsets
        return out


class SpanContext:
    """(recorder, current span id) — the unit that travels across
    threads and the wire."""

    __slots__ = ("recorder", "span_id")

    def __init__(self, recorder: SpanRecorder, span_id: str):
        self.recorder = recorder
        self.span_id = span_id

    def wire(self) -> Tuple[str, str]:
        """(trace_id, span_id) for header / RPC propagation."""
        return self.recorder.trace_id, self.span_id


# -------------------------------------------------- thread propagation

_tl = threading.local()


def current() -> Optional[SpanContext]:
    return getattr(_tl, "ctx", None)


def _set_current(ctx: Optional[SpanContext]) -> Optional[SpanContext]:
    """Raw swap for hot paths (``observability.attributed``); returns
    the previous context so the caller can restore it."""
    prev = getattr(_tl, "ctx", None)
    _tl.ctx = ctx
    return prev


@contextlib.contextmanager
def attach(ctx: Optional[SpanContext]):
    """Install ``ctx`` as this thread's span context. ``None`` is a
    no-op (the current context, if any, stays installed)."""
    if ctx is None:
        yield None
        return
    prev = _set_current(ctx)
    try:
        yield ctx
    finally:
        _set_current(prev)


def run_attached(ctx: Optional[SpanContext], fn, *args, **kwargs):
    """Run ``fn`` under ``ctx`` — the shape pool-submit sites use to
    carry the submitting thread's span context onto a worker thread."""
    with attach(ctx):
        return fn(*args, **kwargs)


# ---------------------------------------------------------- live spans


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, key, value):
        pass


_NOOP = _NoopSpan()


class _LiveSpan:
    __slots__ = ("_ctx", "_name", "_key", "_attrs", "_lane", "_t0",
                 "_id", "_prev")

    def __init__(self, ctx: SpanContext, name: str, key: Optional[str],
                 attrs: Optional[dict], lane: str):
        self._ctx = ctx
        self._name = name
        self._key = key or name
        self._attrs = dict(attrs) if attrs else None
        self._lane = lane

    def set(self, key, value) -> None:
        if self._attrs is None:
            self._attrs = {}
        self._attrs[key] = value

    def __enter__(self):
        rec = self._ctx.recorder
        self._id = rec.unique_span_id(self._key)
        self._t0 = _now_us()
        self._prev = _set_current(SpanContext(rec, self._id))
        return self

    def __exit__(self, exc_type, exc, tb):
        _set_current(self._prev)
        self._ctx.recorder.add(
            self._name, self._id, self._ctx.span_id, self._t0,
            _now_us() - self._t0, attrs=self._attrs, lane=self._lane,
            status="error" if exc_type is not None else "ok")
        return False


def span(name: str, key: Optional[str] = None,
         attrs: Optional[dict] = None, lane: str = "driver"):
    """Context manager recording one span under the thread's current
    context; a cheap no-op singleton when the thread is untraced (the
    sampling gate: no ids, no dicts, no clock reads)."""
    ctx = current()
    if ctx is None:
        return _NOOP
    return _LiveSpan(ctx, name, key, attrs, lane)


def event(name: str, key: Optional[str] = None,
          attrs: Optional[dict] = None, lane: str = "driver",
          ctx: Optional[SpanContext] = None,
          parent_id: Optional[str] = None) -> None:
    """Zero-duration span (cancellations, retries, speculation marks)."""
    ctx = ctx if ctx is not None else current()
    if ctx is None:
        return
    rec = ctx.recorder
    rec.add(name, rec.unique_span_id(key or name),
            parent_id or ctx.span_id, _now_us(), 0, attrs=attrs,
            lane=lane)


# ------------------------------------------------------ trace registry

_reg_lock = threading.Lock()
_recorders: "Dict[str, SpanRecorder]" = {}
_trace_seq = itertools.count(1)


def trace_enabled() -> bool:
    from .analysis import knobs
    return bool(knobs.env_bool("DAFT_TPU_TRACE"))


def recorder_for(trace_id: str) -> Optional[SpanRecorder]:
    with _reg_lock:
        return _recorders.get(trace_id)


def register_recorder(rec: SpanRecorder) -> None:
    with _reg_lock:
        while len(_recorders) >= _MAX_ACTIVE_RECORDERS:
            _recorders.pop(next(iter(_recorders)))
        _recorders[rec.trace_id] = rec


def unregister_recorder(trace_id: str) -> None:
    with _reg_lock:
        _recorders.pop(trace_id, None)


def maybe_start_trace(kind: str = "query") -> Optional[SpanContext]:
    """Start (and register) a trace for a new top-level query — or
    return ``None`` when tracing is off, the query loses the sampling
    draw, or the thread is already inside a trace (the query joins it).
    The sampling decision hashes the deterministic per-process trace
    key, never RNG."""
    if current() is not None:
        return None
    if not trace_enabled():
        return None
    from .analysis import knobs
    seq = next(_trace_seq)
    trace_key = f"{kind}:{seq}"
    rate = knobs.env_float("DAFT_TPU_TRACE_SAMPLE")
    if rate < 1.0 and _hash01(trace_key) >= max(rate, 0.0):
        return None
    trace_id = hashlib.sha256(
        f"daft-trace\x1f{os.getpid()}\x1f{trace_key}".encode()
    ).hexdigest()[:32]
    rec = SpanRecorder(trace_id)
    register_recorder(rec)
    return SpanContext(rec, rec.root_id)


def abort_trace(ctx: Optional[SpanContext],
                status: str = "error") -> None:
    """Close and unregister a trace whose query died before anything
    could adopt it (a planner failure between :func:`maybe_start_trace`
    and the executor's stats context taking ownership). Idempotent and
    no-op for None / already-exported contexts — safe to call from any
    error path. Without this, every failed optimize/translate left its
    recorder registered for the process lifetime (the registry cap made
    it a rotation of leaks rather than growth, but the trace itself was
    silently lost)."""
    if ctx is None:
        return
    rec = ctx.recorder
    if rec is None or getattr(rec, "exported", False):
        return
    rec.exported = True
    rec.finish(status)
    unregister_recorder(rec.trace_id)


def remote_context(trace_id: str, span_id: str,
                   parent_id: Optional[str] = None
                   ) -> Optional[SpanContext]:
    """Rebuild a span context from wire identifiers. In-process workers
    find the driver's live recorder in the registry; a foreign process
    (remote worker) gets ``None`` from here and must buffer its own
    spans for ship-back (``WorkerServer`` does)."""
    rec = recorder_for(trace_id)
    if rec is None:
        return None
    return SpanContext(rec, span_id)


def wire_headers(ctx: Optional[SpanContext] = None) -> Dict[str, str]:
    """Span-context HTTP headers for the shuffle wire (empty when
    untraced)."""
    ctx = ctx if ctx is not None else current()
    if ctx is None:
        return {}
    trace_id, span_id = ctx.wire()
    return {_WIRE_TRACE_HEADER: trace_id, _WIRE_PARENT_HEADER: span_id}


def context_from_headers(headers) -> Optional[SpanContext]:
    """Span context from incoming shuffle-wire headers (None when the
    request is untraced or the trace lives in another process)."""
    try:
        trace_id = headers.get(_WIRE_TRACE_HEADER)
        span_id = headers.get(_WIRE_PARENT_HEADER)
    except Exception:
        return None
    if not trace_id or not span_id:
        return None
    return remote_context(trace_id, span_id)


# ------------------------------------------------------- chrome export

#: lane order for the chrome export's tid assignment: driver layers
#: first, then device, then workers in first-seen order
_LANE_PRIORITY = ("driver", "serving", "planner", "pipeline", "scan",
                  "device", "dev:upload", "dev:compute", "dev:download")


def chrome_trace_events(rec: SpanRecorder) -> List[dict]:
    """Perfetto-loadable event list: one ``X`` (complete) event per
    span on a per-lane tid, plus ``M`` thread-name metadata events.
    Timestamps are rebased to the earliest span and sorted monotonic."""
    spans = sorted(rec.spans(), key=lambda s: (s["ts_us"], s["span_id"]))
    if not spans:
        return []
    base = min(s["ts_us"] for s in spans)
    lanes: Dict[str, int] = {}
    for lane in _LANE_PRIORITY:
        lanes[lane] = len(lanes)
    for s in spans:
        lanes.setdefault(s["lane"], len(lanes))
    pid = os.getpid()
    events: List[dict] = [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
         "args": {"name": lane}}
        for lane, tid in sorted(lanes.items(), key=lambda kv: kv[1])]
    for s in spans:
        args = {"span_id": s["span_id"], "parent_id": s["parent_id"]}
        if s.get("attrs"):
            args.update({k: v for k, v in s["attrs"].items()})
        if s.get("status", "ok") != "ok":
            args["status"] = s["status"]
        events.append({"name": s["name"], "ph": "X",
                       "ts": s["ts_us"] - base, "dur": s["dur_us"],
                       "pid": pid, "tid": lanes[s["lane"]],
                       "args": args})
    return events


def chrome_trace_json(rec: SpanRecorder) -> dict:
    return {"traceEvents": chrome_trace_events(rec),
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": rec.trace_id,
                          "dropped_spans": rec.dropped}}


def validate_chrome_trace(doc: dict) -> List[str]:
    """Schema check for an exported Chrome trace (the ``obs-smoke``
    gate): required event fields, non-negative monotonic timestamps,
    only ``X``/``M``/``B``/``E`` phases with ``B``/``E`` matched per
    (pid, tid). Returns human-readable problems (empty = valid)."""
    problems: List[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    last_ts: Dict[tuple, float] = {}
    open_b: Dict[tuple, int] = {}
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            problems.append(f"event {i} is not an object")
            continue
        ph = e.get("ph")
        if ph not in ("X", "M", "B", "E"):
            problems.append(f"event {i}: unsupported phase {ph!r}")
            continue
        for field in ("name", "pid", "tid"):
            if field not in e:
                problems.append(f"event {i}: missing {field!r}")
        if ph == "M":
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: bad ts {ts!r}")
            continue
        key = (e.get("pid"), e.get("tid"))
        if ts < last_ts.get(key, 0):
            problems.append(
                f"event {i}: non-monotonic ts on lane {key}")
        last_ts[key] = ts
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i}: bad dur {dur!r}")
        elif ph == "B":
            open_b[key] = open_b.get(key, 0) + 1
        elif ph == "E":
            if open_b.get(key, 0) <= 0:
                problems.append(f"event {i}: E without matching B")
            else:
                open_b[key] -= 1
    for key, n in open_b.items():
        if n:
            problems.append(f"lane {key}: {n} unmatched B event(s)")
    return problems


def orphan_spans(rec: SpanRecorder) -> List[dict]:
    """Spans whose parent id resolves to no recorded span (and is not
    the root). The chaos-correctness contract: always empty."""
    ids = rec.span_ids() | {rec.root_id}
    return [s for s in rec.spans()
            if s["parent_id"] not in ids]


# --------------------------------------------------------- OTLP export


def otlp_spans_payload(rec: SpanRecorder) -> dict:
    """The trace as an OTLP/HTTP JSON ExportTraceServiceRequest
    (``/v1/traces``), extending the metrics-only export in
    ``observability.export_otlp``."""
    def _span(s: dict) -> dict:
        out = {
            "traceId": rec.trace_id,
            "spanId": s["span_id"],
            "name": s["name"],
            "kind": 1,  # INTERNAL
            "startTimeUnixNano": str(s["ts_us"] * 1000),
            "endTimeUnixNano": str((s["ts_us"] + s["dur_us"]) * 1000),
            "attributes": [
                {"key": "lane", "value": {"stringValue": s["lane"]}}],
        }
        if s["parent_id"] != s["span_id"]:
            out["parentSpanId"] = s["parent_id"]
        for k, v in (s.get("attrs") or {}).items():
            if isinstance(v, bool):
                val = {"boolValue": v}
            elif isinstance(v, int):
                val = {"intValue": str(v)}
            elif isinstance(v, float):
                val = {"doubleValue": v}
            else:
                val = {"stringValue": str(v)}
            out["attributes"].append({"key": str(k), "value": val})
        if s.get("status", "ok") != "ok":
            out["status"] = {"code": 2}  # ERROR
        return out

    return {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name",
             "value": {"stringValue": "daft_tpu"}}]},
        "scopeSpans": [{
            "scope": {"name": "daft_tpu.tracing"},
            "spans": [_span(s) for s in rec.spans()]}]}]}


# ------------------------------------------------- prometheus /metrics


def _prom_escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _prom_name(prefix: str, raw: str) -> str:
    out = []
    for ch in raw:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    name = "".join(out).strip("_").lower()
    return f"daft_tpu_{prefix}_{name}"


def prometheus_text() -> str:
    """Process-wide counters/gauges in Prometheus text exposition
    format: the serving / shuffle / scan-io / recovery / device-kernel
    planes plus queue-depth and cache-hit-rate gauges. Never raises —
    a plane that fails to import simply contributes nothing."""
    lines: List[str] = []

    def emit(name: str, value, kind: str = "counter",
             help_: str = "") -> None:
        if not isinstance(value, (int, float)):
            return
        lines.append(f"# HELP {name} {help_ or name}")
        lines.append(f"# TYPE {name} {kind}")
        if isinstance(value, float) and value == int(value):
            value = int(value)
        lines.append(f"{name} {value}")

    def plane(prefix: str, counters: Dict[str, float],
              help_: str) -> None:
        for k in sorted(counters):
            emit(_prom_name(prefix, k) + "_total", counters[k],
                 "counter", f"{help_} ({k})")

    try:
        from .distributed import shuffle_service
        plane("shuffle", shuffle_service.shuffle_counters_snapshot(),
              "shuffle data-plane counter")
    except Exception:
        pass
    try:
        from .io import read_planner
        plane("io", read_planner.scan_counters_snapshot(),
              "scan-plane io counter")
    except Exception:
        pass
    try:
        from .execution import memory
        plane("spill", memory.spill_counters_snapshot(),
              "out-of-core spill-tier counter")
    except Exception:
        pass
    try:
        from .execution import governor
        plane("governor", governor.counters_snapshot(),
              "memory-governor backpressure action counter")
        snap = governor.snapshot()
        emit("daft_tpu_rss_bytes", snap["rss_bytes"], "gauge",
             "current process resident set size")
        emit("daft_tpu_rss_peak_bytes", snap["rss_peak_bytes"], "gauge",
             "peak process resident set size since start/reset")
        if snap["limit_bytes"]:
            emit("daft_tpu_memory_limit_bytes", snap["limit_bytes"],
                 "gauge", "configured DAFT_TPU_MEMORY_LIMIT budget")
        emit("daft_tpu_governor_pressured", snap["pressured"], "gauge",
             "1 while RSS sits inside the governor's hysteresis band")
    except Exception:
        pass
    try:
        from .distributed import resilience
        plane("recovery", resilience.counters_snapshot(),
              "resilience recovery counter")
    except Exception:
        pass
    try:
        from .physical import adaptive as _adaptive
        plane("adaptive", _adaptive.counters_snapshot(),
              "self-tuning feedback counter")
    except Exception:
        pass
    try:
        from .device import calibration
        if calibration.enabled():
            emit("daft_tpu_calibration_constants_active",
                 len(calibration.calibrated_names()), "gauge",
                 "cost-model constants currently overridden by the "
                 "calibrated profile")
    except Exception:
        pass
    try:
        from .parallel import exchange
        ex = exchange.exchange_cache_counters()
        emit("daft_tpu_exchange_programs", ex.pop("entries", 0), "gauge",
             "memoized collective exchange programs resident")
        plane("exchange", ex,
              "collective exchange program-cache counter")
    except Exception:
        pass
    try:
        from .distributed import topology
        emit("daft_tpu_exchange_collective_inflight",
             topology.collective_inflight(), "gauge",
             "collective exchange groups currently in flight")
    except Exception:
        pass
    try:
        from . import observability as obs
        plane("obs", obs.obs_counters_snapshot(),
              "observability export counter")
    except Exception:
        pass
    try:
        from .fleet import state_sync
        fleet_counters = state_sync.counters_snapshot()
        if fleet_counters:
            plane("fleet", fleet_counters,
                  "serving-fleet counter (routing, gossip, cache tier)")
    except Exception:
        pass
    try:
        from .analysis import retrace_sanitizer
        plane("retrace", retrace_sanitizer.counters_snapshot(),
              "retrace sanitizer counter")
    except Exception:
        pass
    try:
        from .analysis import plan_sanitizer
        plane("plansan", plan_sanitizer.counters_snapshot(),
              "plan sanitizer contract-check counter")
    except Exception:
        pass
    try:
        from .device import costmodel
        for kind, d in sorted(costmodel.ledger_snapshot(raw=True).items()):
            emit(_prom_name("kernel", f"{kind}_dispatches") + "_total",
                 d.get("dispatches", 0), "counter",
                 f"device dispatches ({kind})")
            emit(_prom_name("kernel", f"{kind}_seconds") + "_total",
                 round(d.get("seconds", 0.0), 6), "counter",
                 f"device kernel seconds ({kind})")
    except Exception:
        pass
    try:
        from . import serving
        sched = serving.shared_scheduler_if_running()
        if sched is not None:
            view = sched.live_view()
            emit("daft_tpu_serving_queue_depth", view.get("queued", 0),
                 "gauge", "queries queued in the serving scheduler")
            emit("daft_tpu_serving_running", view.get("running", 0),
                 "gauge", "queries currently running")
            emit("daft_tpu_serving_admitted_bytes",
                 view.get("admitted_bytes", 0), "gauge",
                 "admission-controller outstanding bytes")
            counters = view.get("counters", {})
            for k in sorted(counters):
                if k.startswith(("plan_cache_", "result_cache_")) \
                        or k in ("submitted", "completed", "failed",
                                 "cancelled") \
                        or k.startswith("rejected_"):
                    emit(_prom_name("serving", k) + "_total",
                         counters[k], "counter",
                         f"serving scheduler counter ({k})")
            for cache in ("plan_cache", "result_cache"):
                hits = counters.get(f"{cache}_hits", 0)
                misses = counters.get(f"{cache}_misses", 0)
                if hits + misses:
                    emit(f"daft_tpu_serving_{cache}_hit_rate",
                         round(hits / (hits + misses), 6), "gauge",
                         f"{cache} hit rate since process start")
    except Exception:
        pass
    emit("daft_tpu_traces_active", len(_recorders), "gauge",
         "span recorders currently registered")
    with _flight_lock:
        emit("daft_tpu_flight_recorder_queries_total", _flight_written,
             "counter", "queries persisted to the flight recorder")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[str, float]:
    """Strict parser for the text exposition format (the scrape gate in
    ``obs-smoke``): every line must be a comment, blank, or
    ``name[{labels}] value [timestamp]`` with a valid metric name and a
    float value. Raises ``ValueError`` on any malformed line."""
    out: Dict[str, float] = {}
    typed: Dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                if parts[1] == "TYPE":
                    if len(parts) < 4 or parts[3] not in (
                            "counter", "gauge", "histogram", "summary",
                            "untyped"):
                        raise ValueError(
                            f"line {lineno}: bad TYPE line {line!r}")
                    typed[parts[2]] = parts[3]
                continue
            raise ValueError(f"line {lineno}: bad comment {line!r}")
        name = line.split("{")[0].split()[0]
        if not name or not (name[0].isalpha() or name[0] in "_:"):
            raise ValueError(f"line {lineno}: bad metric name {line!r}")
        if not all(c.isalnum() or c in "_:" for c in name):
            raise ValueError(f"line {lineno}: bad metric name {line!r}")
        rest = line[len(name):].strip()
        if rest.startswith("{"):
            close = rest.find("}")
            if close < 0:
                raise ValueError(f"line {lineno}: unclosed labels")
            rest = rest[close + 1:].strip()
        fields = rest.split()
        if not fields:
            raise ValueError(f"line {lineno}: missing value")
        try:
            value = float(fields[0])
        except ValueError:
            raise ValueError(
                f"line {lineno}: non-numeric value {fields[0]!r}")
        if len(fields) > 2:
            raise ValueError(f"line {lineno}: trailing garbage")
        out[name] = value
    return out


# ------------------------------------------------------ flight recorder

_flight_lock = threading.Lock()
_flight_written = 0


def _flight_path() -> Optional[str]:
    from .analysis import knobs
    return knobs.env_str("DAFT_TPU_QUERY_LOG") or None


def flight_record(entry: dict) -> None:
    """Append one query record to the flight-recorder JSONL
    (``DAFT_TPU_QUERY_LOG``); rotates the file to ``<path>.1`` when it
    exceeds ``DAFT_TPU_QUERY_LOG_BYTES``. Never raises into the query
    path."""
    global _flight_written
    path = _flight_path()
    if not path:
        return
    from .analysis import knobs
    cap = knobs.env_bytes("DAFT_TPU_QUERY_LOG_BYTES")
    try:
        line = json.dumps(entry, default=str) + "\n"
    except Exception:
        return
    with _flight_lock:
        try:
            if cap and cap > 0:
                try:
                    if os.path.getsize(path) + len(line) > cap:
                        os.replace(path, path + ".1")
                except OSError:
                    pass  # no current file yet
            # daft-lint: allow(blocking-under-lock) -- the size check,
            # rotation and append must be one atomic unit vs concurrent
            # query-finish writers; local log file, one line per query
            with open(path, "a") as f:
                f.write(line)
            _flight_written += 1
        except Exception:
            pass


#: bytes read from the END of each flight-recorder generation per
#: history call — the wanted entries are by construction at the tail;
#: reading whole 16MiB logs per dashboard poll is the alternative
_FLIGHT_TAIL_BYTES = 512 << 10


def flight_history(limit: int = 200) -> List[dict]:
    """Most-recent-first flight-recorder entries (current file, then
    the rotated generation), read from a bounded tail window of each.
    Tolerates torn/partial head lines."""
    path = _flight_path()
    if not path:
        return []
    out: List[dict] = []
    for p in (path, path + ".1"):
        try:
            with open(p, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                start = max(size - _FLIGHT_TAIL_BYTES, 0)
                f.seek(start)
                data = f.read()
        except OSError:
            continue
        lines = data.splitlines()
        if start > 0 and lines:
            lines = lines[1:]  # first line is mid-record: drop it
        for line in reversed(lines):
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
            if len(out) >= limit:
                return out
    return out


def slow_query_ms() -> float:
    from .analysis import knobs
    return knobs.env_float("DAFT_TPU_SLOW_QUERY_MS")


def reset_for_tests() -> None:
    global _flight_written
    with _reg_lock:
        _recorders.clear()
    with _flight_lock:
        _flight_written = 0
