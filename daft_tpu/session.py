"""daft_tpu.session — Session: attached catalogs, temp tables, SQL context.

Parity target: the reference's ``daft/session.py`` (``Session`` :49-507 and
module-level verbs on an ambient session :519-703) over ``src/daft-session``.
The session is the name-resolution root for ``session.sql(...)``: temp tables
shadow catalog tables; unqualified names resolve against the current catalog
and namespace; attached UDFs become SQL-callable functions.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Union

from .catalog import (
    Catalog, Identifier, InMemoryCatalog, MemTable, NotFoundError, Table,
    _as_table, _to_ident,
)


class Session:
    def __init__(self) -> None:
        self._catalogs: Dict[str, Catalog] = {}
        self._tables: Dict[str, Table] = {}       # temp tables (session-scoped)
        self._functions: Dict[str, Any] = {}      # attached UDFs
        self._current_catalog: Optional[str] = None
        self._current_namespace: Optional[Identifier] = None

    @staticmethod
    def _from_env() -> "Session":
        return Session()

    # -- sql ---------------------------------------------------------------
    def sql(self, sql: str):
        """Plan+return a DataFrame for a query against this session's names.

        Name resolution is lazy: the planner calls back into
        ``Session.get_table`` per referenced table (temp tables shadow
        catalog tables; unqualified names resolve against the current
        catalog/namespace — reference ``src/daft-session`` semantics).
        """
        from .sql.planner import SQLPlanner
        return SQLPlanner({}, session=self).plan_statement(sql)

    # -- attach / detach ---------------------------------------------------
    def attach(self, object: Any, alias: Optional[str] = None):
        from .udf import UDF
        if isinstance(object, Catalog):
            return self.attach_catalog(object, alias)
        if isinstance(object, Table):
            return self.attach_table(object, alias)
        if isinstance(object, UDF):
            return self.attach_function(object, alias)
        if isinstance(object, dict):
            return self.attach_catalog(object, alias)
        raise ValueError(f"cannot attach {type(object).__name__}")

    def attach_catalog(self, catalog: Any, alias: Optional[str] = None) -> Catalog:
        cat = Catalog._from_obj(catalog)
        name = alias or cat.name
        if name in self._catalogs:
            raise ValueError(f"catalog {name!r} is already attached")
        self._catalogs[name] = cat
        if self._current_catalog is None:
            self._current_catalog = name
        return cat

    def attach_table(self, table: Any, alias: Optional[str] = None) -> Table:
        tbl = table if isinstance(table, Table) else _as_table(alias or "table", table)
        name = alias or tbl.name
        if name in self._tables:
            raise ValueError(f"table {name!r} is already attached")
        self._tables[name] = tbl
        return tbl

    def attach_function(self, function: Any, alias: Optional[str] = None) -> None:
        name = alias or getattr(function, "name", None) \
            or getattr(getattr(function, "fn", None), "__name__", None)
        if not name:
            raise ValueError("cannot infer function alias; pass alias=")
        self._functions[name.lower()] = function

    def detach_catalog(self, alias: str) -> None:
        if alias not in self._catalogs:
            raise NotFoundError(f"catalog {alias!r} is not attached")
        del self._catalogs[alias]
        if self._current_catalog == alias:
            self._current_catalog = next(iter(self._catalogs), None)

    def detach_table(self, alias: str) -> None:
        if alias not in self._tables:
            raise NotFoundError(f"table {alias!r} is not attached")
        del self._tables[alias]

    def detach_function(self, alias: str) -> None:
        if alias.lower() not in self._functions:
            raise NotFoundError(f"function {alias!r} is not attached")
        del self._functions[alias.lower()]

    # -- create / drop -----------------------------------------------------
    def _default_catalog(self) -> Catalog:
        if self._current_catalog is None:
            self.attach_catalog(InMemoryCatalog("default"))
        return self._catalogs[self._current_catalog]

    def create_namespace(self, identifier) -> None:
        self._default_catalog().create_namespace(identifier)

    def create_namespace_if_not_exists(self, identifier) -> None:
        self._default_catalog().create_namespace_if_not_exists(identifier)

    def create_table(self, identifier, source, **properties) -> Table:
        return self._default_catalog().create_table(identifier, source, **properties)

    def create_table_if_not_exists(self, identifier, source, **properties) -> Table:
        return self._default_catalog().create_table_if_not_exists(
            identifier, source, **properties)

    def create_temp_table(self, identifier: str, source) -> Table:
        tbl = _as_table(identifier, source)
        self._tables[identifier] = tbl
        return tbl

    def drop_namespace(self, identifier) -> None:
        self._default_catalog().drop_namespace(identifier)

    def drop_table(self, identifier) -> None:
        ident = _to_ident(identifier)
        if len(ident) == 1 and str(ident) in self._tables:
            del self._tables[str(ident)]
            return
        # catalog-qualified names resolve like get_table does
        if len(ident) > 1 and ident[0] in self._catalogs:
            self._catalogs[ident[0]].drop_table(ident.drop(1))
            return
        self._default_catalog().drop_table(identifier)

    # -- current catalog / namespace --------------------------------------
    def use(self, identifier=None) -> None:
        if identifier is None:
            self._current_catalog = None
            self._current_namespace = None
            return
        ident = _to_ident(identifier)
        self.set_catalog(ident[0])
        self._current_namespace = ident.drop(1) if len(ident) > 1 else None

    def current_catalog(self) -> Optional[Catalog]:
        return self._catalogs.get(self._current_catalog) \
            if self._current_catalog else None

    def current_namespace(self) -> Optional[Identifier]:
        return self._current_namespace

    def set_catalog(self, identifier: Optional[str]) -> None:
        if identifier is None:
            self._current_catalog = None
            return
        if identifier not in self._catalogs:
            raise NotFoundError(f"catalog {identifier!r} is not attached")
        self._current_catalog = identifier

    def set_namespace(self, identifier) -> None:
        self._current_namespace = _to_ident(identifier) \
            if identifier is not None else None

    # -- lookups -----------------------------------------------------------
    def get_catalog(self, identifier: str) -> Catalog:
        if identifier not in self._catalogs:
            raise NotFoundError(f"catalog {identifier!r} is not attached")
        return self._catalogs[identifier]

    def get_table(self, identifier) -> Table:
        ident = _to_ident(identifier)
        if len(ident) == 1 and str(ident) in self._tables:
            return self._tables[str(ident)]
        # fully-qualified: first part names an attached catalog
        if len(ident) > 1 and ident[0] in self._catalogs:
            return self._catalogs[ident[0]].get_table(ident.drop(1))
        cat = self.current_catalog()
        if cat is not None:
            ns = self._current_namespace
            if ns is not None and cat.has_table(ns + ident):
                return cat.get_table(ns + ident)
            return cat.get_table(ident)
        raise NotFoundError(f"table {ident} not found")

    def has_catalog(self, identifier: str) -> bool:
        return identifier in self._catalogs

    def has_namespace(self, identifier) -> bool:
        cat = self.current_catalog()
        return bool(cat) and cat.has_namespace(identifier)

    def has_table(self, identifier) -> bool:
        try:
            self.get_table(identifier)
            return True
        except NotFoundError:
            return False

    def list_catalogs(self, pattern: Optional[str] = None) -> List[str]:
        out = sorted(self._catalogs)
        return [c for c in out if not pattern or c.startswith(pattern)]

    def list_namespaces(self, pattern: Optional[str] = None) -> List[Identifier]:
        cat = self.current_catalog()
        return cat.list_namespaces(pattern) if cat else []

    def list_tables(self, pattern: Optional[str] = None) -> List[Identifier]:
        out = [Identifier(t) for t in sorted(self._tables)]
        cat = self.current_catalog()
        if cat:
            out += cat.list_tables(pattern)
        return [t for t in out if not pattern or str(t).startswith(pattern)]

    def read_table(self, identifier, **options):
        return self.get_table(identifier).read(**options)

    def write_table(self, identifier, df, mode: str = "append", **options) -> None:
        self.get_table(identifier).write(df, mode=mode, **options)


_SESSION: Optional[Session] = None
# two racing first callers used to each build a Session, and attachments
# made through the loser silently vanished (daft-lint
# unguarded-global-mutation find)
_session_lock = threading.Lock()


def _session() -> Session:
    global _SESSION
    if _SESSION is not None:    # hot path: no lock once built
        return _SESSION
    with _session_lock:
        if _SESSION is None:
            _SESSION = Session()
        return _SESSION


def current_session() -> Session:
    return _session()


# module-level verbs over the ambient session (reference session.py:519-703)
def attach(object, alias=None): return _session().attach(object, alias)
def attach_catalog(catalog, alias=None): return _session().attach_catalog(catalog, alias)
def attach_table(table, alias=None): return _session().attach_table(table, alias)
def attach_function(function, alias=None): return _session().attach_function(function, alias)
def detach_catalog(alias): return _session().detach_catalog(alias)
def detach_table(alias): return _session().detach_table(alias)
def detach_function(alias): return _session().detach_function(alias)
def create_namespace(identifier): return _session().create_namespace(identifier)
def create_namespace_if_not_exists(identifier): return _session().create_namespace_if_not_exists(identifier)
def create_table(identifier, source, **p): return _session().create_table(identifier, source, **p)
def create_table_if_not_exists(identifier, source, **p): return _session().create_table_if_not_exists(identifier, source, **p)
def create_temp_table(identifier, source): return _session().create_temp_table(identifier, source)
def drop_namespace(identifier): return _session().drop_namespace(identifier)
def drop_table(identifier): return _session().drop_table(identifier)
def current_catalog(): return _session().current_catalog()
def current_namespace(): return _session().current_namespace()
def get_catalog(identifier): return _session().get_catalog(identifier)
def get_table(identifier): return _session().get_table(identifier)
def has_catalog(identifier): return _session().has_catalog(identifier)
def has_namespace(identifier): return _session().has_namespace(identifier)
def has_table(identifier): return _session().has_table(identifier)
def list_catalogs(pattern=None): return _session().list_catalogs(pattern)
def list_namespaces(pattern=None): return _session().list_namespaces(pattern)
def list_tables(pattern=None): return _session().list_tables(pattern)
def read_table(identifier, **options): return _session().read_table(identifier, **options)
def write_table(identifier, df, mode="append", **options): return _session().write_table(identifier, df, mode=mode, **options)
def set_catalog(identifier): return _session().set_catalog(identifier)
def set_namespace(identifier): return _session().set_namespace(identifier)
def use(identifier=None): return _session().use(identifier)
