"""Benchmark driver: TPC-H Q1 through the daft_tpu engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Structure (hang-proof by construction, round-1 postmortem):
1. baseline: the same Q1 via Arrow C++ compute (pyarrow TableGroupBy) on CPU
   — the reference engine's substrate — measured in-process.
2. host tier: the full daft_tpu DataFrame pipeline with the device tier
   disabled (DAFT_TPU_DEVICE=0), in-process. This never touches the JAX
   backend, so it cannot hang; its number is always captured.
3. device tier: the same query with the device tier enabled, in a CHILD
   process under a timeout (BENCH_DEVICE_TIMEOUT, default 600 s). A wedged
   TPU plugin (round-1 failure: lazy PJRT init hung forever) kills only the
   child; the engine-side watchdog (daft_tpu/device/backend.py) additionally
   pins the child to the host tier if backend init times out.
The reported number is the best tier. vs_baseline = baseline_s / ours_s
(>1 → we're faster). BENCH_SF / BENCH_PARTS control the dataset.
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

SF = float(os.environ.get("BENCH_SF", "1"))
PARTS = int(os.environ.get("BENCH_PARTS", "8"))
DATA = os.path.join(REPO, ".cache", f"tpch_sf{SF}")
DEVICE_TIMEOUT = float(os.environ.get("BENCH_DEVICE_TIMEOUT", "600"))


def ensure_data():
    marker = os.path.join(DATA, "lineitem")
    if not os.path.isdir(marker):
        from benchmarking.tpch.datagen import generate_tpch
        print(f"generating TPC-H SF{SF} …", file=sys.stderr, flush=True)
        generate_tpch(DATA, SF, PARTS)
    return DATA


def run_daft_q1():
    import daft_tpu as dt
    from benchmarking.tpch import queries as Q

    def get_df(name):
        return dt.read_parquet(f"{DATA}/{name}/*.parquet")
    # warm once (compile cache + IO cache), then measure
    t0 = time.time()
    out = Q.q1(get_df).to_pydict()
    warm = time.time() - t0
    t1 = time.time()
    out = Q.q1(get_df).to_pydict()
    hot = time.time() - t1
    return out, warm, hot


def run_daft_q6():
    """Second device-tier data point: selective filter + global agg (the
    fused scan→filter→reduce fragment shape)."""
    import daft_tpu as dt
    from benchmarking.tpch import queries as Q

    def get_df(name):
        return dt.read_parquet(f"{DATA}/{name}/*.parquet")
    t0 = time.time()
    out = Q.q6(get_df).to_pydict()
    warm = time.time() - t0
    t1 = time.time()
    out = Q.q6(get_df).to_pydict()
    hot = time.time() - t1
    return out, warm, hot


def run_arrow_baseline():
    import pyarrow.dataset as pads
    import pyarrow.compute as pc
    t0 = time.time()
    t = pads.dataset(os.path.join(DATA, "lineitem")).to_table()
    t = t.filter(pc.field("l_shipdate") <= datetime.date(1998, 9, 2))
    disc = pc.multiply(t.column("l_extendedprice"),
                       pc.subtract(1.0, t.column("l_discount")))
    charge = pc.multiply(disc, pc.add(1.0, t.column("l_tax")))
    t = t.append_column("disc_price", disc).append_column("charge", charge)
    g = t.group_by(["l_returnflag", "l_linestatus"]).aggregate(
        [("l_quantity", "sum"), ("l_extendedprice", "sum"),
         ("disc_price", "sum"), ("charge", "sum"), ("l_quantity", "mean"),
         ("l_extendedprice", "mean"), ("l_discount", "mean"),
         ("l_quantity", "count")])
    g = g.sort_by([("l_returnflag", "ascending"), ("l_linestatus", "ascending")])
    return g, time.time() - t0


def _device_child():
    """Child-process entry: run Q1 (+Q6) with the device tier on, print one
    JSON line. Q1 prints FIRST so a Q6 compile stall can't zero the main
    measurement."""
    os.environ["DAFT_TPU_DEVICE"] = "1"
    out, warm, hot = run_daft_q1()
    from daft_tpu.device import backend as dbackend
    print(json.dumps({
        "warm": warm, "hot": hot, "groups": len(out["l_returnflag"]),
        "backend": dbackend.backend_name() or "host-fallback",
    }), flush=True)
    _, q6_warm, q6_hot = run_daft_q6()
    print(json.dumps({"q6_warm": q6_warm, "q6_hot": q6_hot}), flush=True)


def _try_device_tier():
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--device-child"],
            capture_output=True, text=True, timeout=DEVICE_TIMEOUT,
            cwd=REPO, env={**os.environ, "DAFT_TPU_DEVICE": "1"})
    except subprocess.TimeoutExpired as exc:
        # keep whatever the child already measured (Q1 prints first, so a
        # Q6 compile stall cannot zero the main measurement)
        print("device tier: timed out; using partial output",
              file=sys.stderr)
        partial = exc.stdout or b""
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        merged = {}
        for line in partial.strip().splitlines():
            try:
                parsed = json.loads(line)
            except ValueError:
                continue
            if isinstance(parsed, dict):
                merged.update(parsed)
        return merged or None
    if proc.returncode != 0:
        print(f"device tier: child failed rc={proc.returncode}\n"
              f"{proc.stderr[-2000:]}", file=sys.stderr)
        return None
    # the child emits one JSON line per measured query; merge them
    merged = {}
    for line in proc.stdout.strip().splitlines():
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict):
            merged.update(parsed)
    return merged or None


def main():
    ensure_data()
    import pyarrow.parquet as pq
    import glob as g
    nrows = sum(pq.ParquetFile(p).metadata.num_rows
                for p in g.glob(f"{DATA}/lineitem/*.parquet"))

    base_tbl, base_s = run_arrow_baseline()

    # host tier first: hang-free, guarantees a number is always reported
    os.environ["DAFT_TPU_DEVICE"] = "0"
    out, host_warm, host_hot = run_daft_q1()
    assert len(out["l_returnflag"]) == base_tbl.num_rows, \
        (len(out["l_returnflag"]), base_tbl.num_rows)

    os.environ["DAFT_TPU_DEVICE"] = "0"
    _, q6_host_warm, q6_host_hot = run_daft_q6()
    detail = {
        "host_warm_s": round(host_warm, 3), "host_hot_s": round(host_hot, 3),
        "arrow_cpu_baseline_s": round(base_s, 3), "lineitem_rows": nrows,
        "q6_host_hot_s": round(min(q6_host_warm, q6_host_hot), 3),
        "backend": "host",
    }
    ours = min(host_warm, host_hot)

    dev = _try_device_tier()
    if dev is not None and dev.get("backend") == "host-fallback":
        # the child's watchdog pinned it to the host tier: there was no
        # device measurement — don't report one.
        detail["device_backend"] = "host-fallback"
        dev = None
    if dev is not None and dev.get("groups") == base_tbl.num_rows:
        detail["device_warm_s"] = round(dev["warm"], 3)
        detail["device_hot_s"] = round(dev["hot"], 3)
        detail["device_backend"] = dev.get("backend")
        if "q6_hot" in dev:
            detail["q6_device_hot_s"] = round(dev["q6_hot"], 3)
        if dev["hot"] < ours:
            ours = dev["hot"]
            detail["backend"] = dev.get("backend", "device")

    print(json.dumps({
        "metric": f"tpch_q1_sf{SF}_rows_per_sec_per_chip",
        "value": round(nrows / ours, 1),
        "unit": "rows/s",
        "vs_baseline": round(base_s / ours, 3),
        "detail": detail,
    }))


if __name__ == "__main__":
    if "--device-child" in sys.argv:
        _device_child()
    else:
        main()
