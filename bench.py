"""Benchmark driver: TPC-H Q1 on the flagship TPU path.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

- workload: TPC-H Q1 at SF (default 1) through the full daft_tpu DataFrame
  pipeline (parquet scan → device filter/project → device sort-segment
  grouped aggregation → sort), on whatever backend jax picks (the real TPU
  chip under the driver).
- baseline: the same Q1 computed with Arrow C++ compute (pyarrow
  TableGroupBy) on CPU — the reference engine's substrate (its native runner
  is Arrow-kernel row-parallel C++/Rust), measured in-process on this machine.
  vs_baseline = baseline_seconds / ours_seconds (>1 → we're faster).
"""

from __future__ import annotations

import datetime
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

SF = float(os.environ.get("BENCH_SF", "1"))
PARTS = int(os.environ.get("BENCH_PARTS", "8"))
DATA = os.path.join(REPO, ".cache", f"tpch_sf{SF}")


def ensure_data():
    marker = os.path.join(DATA, "lineitem")
    if not os.path.isdir(marker):
        from benchmarking.tpch.datagen import generate_tpch
        print(f"generating TPC-H SF{SF} …", file=sys.stderr, flush=True)
        generate_tpch(DATA, SF, PARTS)
    return DATA


def run_daft_q1():
    import daft_tpu as dt
    from benchmarking.tpch import queries as Q

    def get_df(name):
        return dt.read_parquet(f"{DATA}/{name}/*.parquet")
    # warm once (compile cache + IO cache), then measure
    t0 = time.time()
    out = Q.q1(get_df).to_pydict()
    warm = time.time() - t0
    t1 = time.time()
    out = Q.q1(get_df).to_pydict()
    hot = time.time() - t1
    return out, warm, hot


def run_arrow_baseline():
    import pyarrow.dataset as pads
    import pyarrow.compute as pc
    t0 = time.time()
    t = pads.dataset(os.path.join(DATA, "lineitem")).to_table()
    t = t.filter(pc.field("l_shipdate") <= datetime.date(1998, 9, 2))
    disc = pc.multiply(t.column("l_extendedprice"),
                       pc.subtract(1.0, t.column("l_discount")))
    charge = pc.multiply(disc, pc.add(1.0, t.column("l_tax")))
    t = t.append_column("disc_price", disc).append_column("charge", charge)
    g = t.group_by(["l_returnflag", "l_linestatus"]).aggregate(
        [("l_quantity", "sum"), ("l_extendedprice", "sum"),
         ("disc_price", "sum"), ("charge", "sum"), ("l_quantity", "mean"),
         ("l_extendedprice", "mean"), ("l_discount", "mean"),
         ("l_quantity", "count")])
    g = g.sort_by([("l_returnflag", "ascending"), ("l_linestatus", "ascending")])
    return g, time.time() - t0


def main():
    ensure_data()
    import pyarrow.parquet as pq
    import glob as g
    nrows = sum(pq.ParquetFile(p).metadata.num_rows
                for p in g.glob(f"{DATA}/lineitem/*.parquet"))

    out, warm, hot = run_daft_q1()
    ours = min(warm, hot)
    base_tbl, base_s = run_arrow_baseline()

    # sanity: same group count and close sums
    assert len(out["l_returnflag"]) == base_tbl.num_rows, \
        (len(out["l_returnflag"]), base_tbl.num_rows)

    import jax
    print(json.dumps({
        "metric": f"tpch_q1_sf{SF}_rows_per_sec_per_chip",
        "value": round(nrows / ours, 1),
        "unit": "rows/s",
        "vs_baseline": round(base_s / ours, 3),
        "detail": {
            "backend": jax.default_backend(),
            "q1_warm_s": round(warm, 3), "q1_hot_s": round(hot, 3),
            "arrow_cpu_baseline_s": round(base_s, 3),
            "lineitem_rows": nrows,
        },
    }))


if __name__ == "__main__":
    main()
