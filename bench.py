"""Benchmark driver: all five BASELINE.json config families through the
daft_tpu engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

Config families (BASELINE.json):
1. TPC-H Q1 @ SF1  — the headline metric (rows/s/chip), host + device tiers
2. TPC-H Q3/Q5/Q10 @ SF10 — 3-way joins + aggregate (runs when the SF10
   dataset is present or BENCH_SF10=1 generates it; ~25 min one-time gen)
3. TPC-H full Q1–Q22 — per-query hot + total wall-clock @ SF1 always, and
   @ SF10 when present
4. TPC-DS Q47/Q63/Q89 — window/rolling trio via the SQL frontend
5. LAION-style multimodal — PNG decode → resize → random-projection
   embedding (device matmul) → cosine sim → groupby

Structure (hang-proof AND deadline-proof by construction; round-1 and
round-3 postmortems):
- a GLOBAL wall-clock budget (`BENCH_TOTAL_BUDGET_S`, default 600 s) is
  enforced across all sections: each checks the remaining budget before
  starting; sections that don't fit are named in `skipped_sections` and
  the single JSON line is always emitted within the budget.
- the Arrow baseline is pinned (best-of-3, persisted per dataset) so the
  headline `vs_baseline` denominator is stable across runs.
- any section failure lands in the top-level `section_errors`, never
  silently inside a detail dict.
- the Arrow CPU baseline and the host tier (DAFT_TPU_DEVICE=0) run
  in-process: they never touch the JAX backend and cannot hang.
- the device tier runs in a CHILD process under BENCH_DEVICE_TIMEOUT
  (default 900 s), printing one JSON line per completed section so a stall
  only loses the sections after it. A wedged TPU plugin kills the child,
  never the driver; the engine watchdog additionally pins a dead backend
  to the host tier.
The reported headline is the best tier on Q1@SF1. vs_baseline =
arrow_baseline_s / ours_s (>1 → we're faster).
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

SF = float(os.environ.get("BENCH_SF", "1"))
PARTS = int(os.environ.get("BENCH_PARTS", "8"))
# _v2: chunked datagen (different RNG streams) — old caches are a different dataset
DATA = os.path.join(REPO, ".cache", f"tpch_sf{SF}_v2")
SF10_DATA = os.path.join(REPO, ".cache", "tpch_sf10.0_v2")
# version-stamped: regenerates when the datagen schema grows
TPCDS_DATA = os.path.join(REPO, ".cache", "tpcds_s1_v3")
LAION_DATA = os.path.join(REPO, ".cache", "laion_4k")
DEVICE_TIMEOUT = float(os.environ.get("BENCH_DEVICE_TIMEOUT", "900"))

# Global wall-clock budget (round-3 postmortem: two of three driver runs
# timed out because per-section budgets never summed to a bound). EVERY
# section checks the remaining budget before starting; whatever doesn't fit
# is named in `skipped_sections` and the one JSON line is still emitted.
# 480 (not 600): sections check the budget BEFORE starting a query, so a
# long SF10 query that starts at T-1 overruns by its own duration (~90s
# worst observed single query). 480 + 90 stays inside every driver window
# that 600 nominally targeted (round-3 postmortem: rc=124 twice).
TOTAL_BUDGET = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "480"))
_T0 = time.time()


def _remaining() -> float:
    return TOTAL_BUDGET - (time.time() - _T0)

TPCH_QUERIES = [f"q{i}" for i in range(1, 23)]


def ensure_data():
    if not os.path.isdir(os.path.join(DATA, "lineitem")):
        from benchmarking.tpch.datagen import generate_tpch
        print(f"generating TPC-H SF{SF} …", file=sys.stderr, flush=True)
        generate_tpch(DATA, SF, PARTS)
    if os.environ.get("BENCH_SF10") == "1" \
            and not os.path.isdir(os.path.join(SF10_DATA, "lineitem")):
        from benchmarking.tpch.datagen import generate_tpch
        print("generating TPC-H SF10 (one-time, ~25 min) …",
              file=sys.stderr, flush=True)
        generate_tpch(SF10_DATA, 10.0, 16)
    if not os.path.isdir(os.path.join(TPCDS_DATA, "store_sales")):
        from benchmarking.tpcds.datagen import generate_tpcds
        print("generating TPC-DS …", file=sys.stderr, flush=True)
        generate_tpcds(TPCDS_DATA, scale=1.0)
    if not os.path.isdir(LAION_DATA):
        _gen_laion(LAION_DATA)


def _gen_laion(root: str, n: int = 4096, px: int = 64):
    """Synthetic LAION-like shard: (id, label, png) parquet. Labels are the
    dominant color channel so the downstream groupby has semantics."""
    import io as _io

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from PIL import Image
    rng = np.random.default_rng(7)
    labels, blobs = [], []
    for i in range(n):
        lab = i % 3
        img = rng.integers(0, 96, size=(px, px, 3), dtype=np.uint8)
        img[..., lab] += 128
        b = _io.BytesIO()
        Image.fromarray(img).save(b, format="PNG")
        labels.append("rgb"[lab])
        blobs.append(b.getvalue())
    os.makedirs(root, exist_ok=True)
    pq.write_table(
        pa.table({"id": pa.array(range(n), pa.int64()),
                  "label": pa.array(labels),
                  "png": pa.array(blobs, pa.large_binary())}),
        os.path.join(root, "images.parquet"))


# --------------------------------------------------------------- sections

def _get_df_factory(root):
    import daft_tpu as dt

    def get_df(name):
        return dt.read_parquet(f"{root}/{name}/*.parquet")
    return get_df


def run_tpch_query(root, qname: str):
    """(warm_s, hot_s) for one TPC-H query over `root`."""
    from benchmarking.tpch import queries as Q
    get_df = _get_df_factory(root)
    fn = getattr(Q, qname)
    t0 = time.time()
    out = fn(get_df).to_pydict()
    warm = time.time() - t0
    t0 = time.time()
    fn(get_df).to_pydict()
    hot = time.time() - t0
    return out, warm, hot


def _decisions_delta(before: dict, after: dict) -> dict:
    """Flattened per-kind strategy-pick deltas from costmodel's nested
    ``decision_counts`` (``{kind: {side: n}}`` or ``{kind: n}``)."""
    out = {}
    for kind, v in after.items():
        if isinstance(v, dict):
            b = before.get(kind) if isinstance(before.get(kind), dict) \
                else {}
            for side, n in v.items():
                d = n - b.get(side, 0)
                if d:
                    out[f"{kind}_{side}"] = int(d)
        else:
            d = v - (before.get(kind) or 0)
            if d:
                out[kind] = int(d)
    return out


def _rich_counters_start() -> dict:
    """Per-query counter bookends for the scale-trajectory artifact:
    spill plane, governor plane, adaptive (replan) plane, cost-model
    strategy picks, and a fresh peak-RSS baseline."""
    from daft_tpu.device import costmodel as _cm
    from daft_tpu.execution import governor as _gov
    from daft_tpu.execution import memory as _mem
    try:
        from daft_tpu.physical import adaptive as _ad
        ad0 = _ad.counters_snapshot()
    except Exception:
        ad0 = {}
    return {"spill": _mem.spill_counters_snapshot(),
            "gov": _gov.counters_snapshot(), "adaptive": ad0,
            "decisions": json.loads(json.dumps(_cm.decision_counts)),
            "rss0": _gov.reset_peak()}


def _rich_counters_finish(s0: dict) -> dict:
    """The per-query record the scale bench commits: spill bytes (logical
    + post-codec disk), partitions/recursion depth, governor actions,
    peak RSS, replan counts, exchange rung changes, strategy picks."""
    from daft_tpu.device import costmodel as _cm
    from daft_tpu.execution import governor as _gov
    from daft_tpu.execution import memory as _mem
    rec: dict = {}
    sd = _mem.spill_counters_delta(s0["spill"])
    if sd.get("bytes_written") or sd.get("joins_partitioned"):
        depths = [int(k[len("recursions_d"):]) for k in sd
                  if k.startswith("recursions_d")]
        rec["spill"] = {
            "bytes_written": int(sd.get("bytes_written", 0)),
            "disk_bytes_written": int(sd.get("disk_bytes_written", 0)),
            "partitions": int(sd.get("partitions_spilled", 0)),
            "recursions": int(sd.get("recursions", 0)),
            "max_depth": max(depths) if depths else 0,
        }
    gd = _gov.counters_delta(s0["gov"])
    if gd:
        rec["governor"] = {k: int(v) for k, v in sorted(gd.items())}
    rec["rss_peak_bytes"] = int(_gov.peak_rss_bytes())
    try:
        from daft_tpu.physical import adaptive as _ad
        ad = _ad.counters_delta(s0["adaptive"])
    except Exception:
        ad = {}
    replans = sum(int(ad.get(k, 0)) for k in
                  ("combine_flips", "exchange_repicks",
                   "broadcast_demotions", "est_rewrites"))
    if replans:
        rec["replans"] = replans
    if ad.get("exchange_repicks"):
        rec["exchange_repicks"] = int(ad["exchange_repicks"])
    picks = _decisions_delta(s0["decisions"], _cm.decision_counts)
    if picks:
        rec["strategy_picks"] = picks
    return rec


def run_tpch_suite(root, queries=TPCH_QUERIES, budget_s: float = 1e9,
                   rich: bool = False):
    """Hot per-query times + totals. Respects a wall-clock budget:
    queries past it are skipped, named in the result, AND itemized per
    query as ``{"skipped": "budget", "remaining_s": ...}`` so the
    artifact shows exactly how much budget each skipped query saw.
    ``rich=True`` (the scale-trajectory mode) additionally records each
    query's spill bytes (logical + disk), spill partitions/recursion
    depth, governor actions, peak RSS, replan count, and strategy
    picks. Each query's spill-tier logical bytes (both runs) ride along
    either way so out-of-core rounds carry per-query spill evidence."""
    from daft_tpu.execution import memory as _mem
    per_q = {}
    rich_q = {}
    spill_q = {}
    skipped = []
    t_start = time.time()
    total_hot = 0.0
    for qn in queries:
        remaining = budget_s - (time.time() - t_start)
        if remaining < 0:
            skipped.append(qn)
            per_q[qn] = {"skipped": "budget",
                         "remaining_s": round(remaining, 1)}
            continue
        s0 = _rich_counters_start() if rich \
            else {"spill": _mem.spill_counters_snapshot()}
        try:
            _, warm, hot = run_tpch_query(root, qn)
        except Exception as exc:  # a failing query must not kill the bench
            per_q[qn] = {"error": str(exc)[:200]}
            continue
        if rich:
            rq = _rich_counters_finish(s0)
            rq["hot_s"] = round(min(warm, hot), 3)
            rich_q[qn] = rq
            sd = {"bytes_written":
                  rq.get("spill", {}).get("bytes_written", 0)}
        else:
            sd = _mem.spill_counters_delta(s0["spill"])
        if sd.get("bytes_written"):
            spill_q[qn] = int(sd["bytes_written"])
        per_q[qn] = round(min(warm, hot), 3)
        total_hot += min(warm, hot)
    out = {"per_query_hot_s": per_q, "total_hot_s": round(total_hot, 3)}
    if rich_q:
        out["per_query"] = rich_q
    if spill_q:
        out["per_query_spill_bytes"] = spill_q
    if skipped:
        out["skipped"] = skipped
    return out


def run_tpcds_trio(root):
    from benchmarking.tpcds import queries as Q
    get_df = _get_df_factory(root)
    out = {}
    for qnum in (47, 63, 89):
        t0 = time.time()
        Q.run(qnum, get_df).to_pydict()
        warm = time.time() - t0
        t0 = time.time()
        Q.run(qnum, get_df).to_pydict()
        out[f"q{qnum}_hot_s"] = round(min(warm, time.time() - t0), 3)
    return out


def run_laion(root):
    """decode → resize → 128-d random-projection embedding → cosine sim →
    groupby(label). The embed matmul is the MXU-shaped step: on the device
    tier it runs as one jit batched matmul; host tier uses numpy."""
    import numpy as np

    import daft_tpu as dt
    from daft_tpu import col
    from daft_tpu.datatype import DataType

    rng = np.random.default_rng(3)
    P = rng.standard_normal((32 * 32 * 3, 128)).astype(np.float32)
    qv = rng.standard_normal(128).astype(np.float32)
    qv /= np.linalg.norm(qv)

    def _embed_on_device() -> bool:
        """The embed matmul goes to the accelerator only when the measured
        link can afford the per-batch transfers (the engine's own cost
        model) — on a tunneled chip the MXU win can't repay ~40 MB/s
        freight, on a local chip it can."""
        if os.environ.get("DAFT_TPU_DEVICE", "1") == "0":
            return False
        from daft_tpu.device import costmodel
        n, d_in, d_out = 4096, 32 * 32 * 3, 128
        return costmodel.row_output_op_wins(
            bytes_up=n * d_in * 4, bytes_down=n * d_out * 4)

    use_device = _embed_on_device()

    @dt.udf(return_dtype=DataType.float32())
    def cos_sim(images):
        arrs = images.to_pylist()
        if not arrs:
            return []
        x = np.stack([np.asarray(a, dtype=np.float32).reshape(-1)
                      for a in arrs])
        x /= 255.0
        if use_device:
            import jax.numpy as jnp
            emb = np.asarray(jnp.asarray(x) @ jnp.asarray(P))
        else:
            emb = x @ P
        norms = np.linalg.norm(emb, axis=1)
        norms[norms == 0] = 1.0
        return (emb @ qv / norms).tolist()

    def pipeline():
        df = dt.read_parquet(os.path.join(root, "images.parquet"))
        df = df.with_column("img", col("png").image.decode(mode="RGB"))
        df = df.with_column("small", col("img").image.resize(32, 32))
        df = df.with_column("sim", cos_sim(col("small")))
        return (df.groupby("label")
                .agg(col("sim").mean().alias("mean_sim"),
                     col("sim").count().alias("n"))
                .sort("label").to_pydict())

    t0 = time.time()
    out = pipeline()
    warm = time.time() - t0
    t0 = time.time()
    pipeline()
    hot = time.time() - t0
    n_imgs = sum(out["n"])
    best = min(warm, hot)
    return {"hot_s": round(best, 3),
            "images_per_s": round(n_imgs / best, 1),
            "groups": len(out["label"])}


def run_chaos(root):
    """``--chaos``: one distributed TPC-H query (Q3) under a fixed seeded
    fault spec covering all three injection sites. Records the
    recovery-event counters and whether the chaotic answer matched the
    fault-free one — the artifact's evidence that the resilience plane
    recovers real queries, not just unit fixtures."""
    import daft_tpu.context as dctx
    from benchmarking.tpch import queries as Q
    from daft_tpu.distributed import resilience as rz
    from daft_tpu.runners.distributed_runner import DistributedRunner

    get_df = _get_df_factory(root)
    baseline = Q.q3(get_df).to_pydict()

    env = {"DAFT_TPU_FAULT_SPEC": "task:0.05,fetch:0.05,crash:0.05",
           "DAFT_TPU_FAULT_SEED": "1",
           "DAFT_TPU_DISTRIBUTED_SHUFFLE": "flight",
           "DAFT_TPU_RETRY_BACKOFF": "0.02"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    rz.reset_for_tests()
    runner = DistributedRunner(num_workers=3)
    old = dctx.get_context()._runner
    dctx.get_context().set_runner(runner)
    t0 = time.time()
    try:
        chaotic = Q.q3(get_df).to_pydict()
    finally:
        dctx.get_context().set_runner(old)
        if runner._manager is not None:  # don't leak worker pools into
            runner._manager.shutdown()   # the timed sections that follow
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    elapsed = time.time() - t0

    def canon(d):
        cols = sorted(d)
        return [tuple(round(v, 6) if isinstance(v, float) else v
                      for v in row)
                for row in zip(*(d[c] for c in cols))]

    counters = rz.counters_snapshot()
    rz.reset_for_tests()
    return {"query": "q3", "spec": env["DAFT_TPU_FAULT_SPEC"],
            "seed": env["DAFT_TPU_FAULT_SEED"],
            "match": canon(chaotic) == canon(baseline),
            "elapsed_s": round(elapsed, 3),
            "recovery_events": {k: v for k, v in sorted(counters.items())}}


def run_spill_bench():
    """``--spill``: out-of-core execution bench — a grace hash join plus
    a near-unique-key group-by under a FORCED tiny memory budget vs the
    unbounded in-memory run. Records parity (must be bit-exact), wall
    ratios, and the spill evidence (disk bytes written/read, radix
    recursions, per-store peak residency — the peak-RSS claim).

    r23 adds the fast-path A/B: the same spilled workload runs once on
    the LEGACY plane (serial writes, no codec — the r19 path, forced via
    DAFT_TPU_SPILL_IO_PARALLELISM=0 + compression none) and once on the
    fast plane (bounded writer pool + lz4 + prefetch-piped reads); both
    walls and both on-disk byte totals land in the artifact, so the
    before/after claim is a committed number, not a narrative."""
    import numpy as np

    import daft_tpu as dt
    from daft_tpu import col
    from daft_tpu.execution import memory as mem

    n = 400_000
    k = np.arange(n) % 120_000
    left = dt.from_pydict({"k": k.tolist(), "v": np.arange(n).tolist()})
    right = dt.from_pydict({"k": k[: n // 2].tolist(),
                            "w": (np.arange(n // 2) * 3).tolist()})

    def join_q():
        return _canon_rows(left.join(right, on="k", strategy="hash")
                           .groupby("k")
                           .agg(col("v").sum(), col("w").sum())
                           .to_pydict())

    def agg_q():
        return _canon_rows(left.groupby("k").agg(col("v").sum())
                           .to_pydict())

    # discarded warm-up pass: plan/translate caches and jit traces are
    # one-time costs — charging them to whichever side runs first would
    # skew the spilled-vs-in-memory ratio (both timed passes below run
    # warm)
    join_q()
    agg_q()
    t0 = time.time()
    ref_join = join_q()
    ref_agg = agg_q()
    in_mem_s = time.time() - t0

    def spilled_pass(extra_env):
        env = {"DAFT_TPU_MEMORY_LIMIT": "2MB", "DAFT_TPU_SPILL_AGG": "1"}
        env.update(extra_env)
        saved = {kk: os.environ.get(kk) for kk in env}
        os.environ.update(env)
        mem._spill_ipc_cache.clear()
        s0 = mem.spill_counters_snapshot()
        t0 = time.time()
        try:
            sj = join_q()
            sa = agg_q()
        finally:
            for kk, v in saved.items():
                if v is None:
                    os.environ.pop(kk, None)
                else:
                    os.environ[kk] = v
            mem._spill_ipc_cache.clear()
        wall = time.time() - t0
        sd = mem.spill_counters_delta(s0)
        return sj, sa, wall, sd

    # best-of-2 per plane: on a 1-core box a single spilled pass sees
    # multi-hundred-ms scheduler noise, which would drown the A/B signal
    legacy_env = {"DAFT_TPU_SPILL_IO_PARALLELISM": "0",
                  "DAFT_TPU_SPILL_COMPRESSION": "none"}
    fast_env = {"DAFT_TPU_SPILL_IO_PARALLELISM": "4",
                "DAFT_TPU_SPILL_COMPRESSION": "lz4"}
    lj, la, legacy_s, legacy_sd = spilled_pass(legacy_env)
    _, _, legacy_s2, _ = spilled_pass(legacy_env)
    legacy_s = min(legacy_s, legacy_s2)
    spilled_join, spilled_agg, spilled_s, sd = spilled_pass(fast_env)
    _, _, fast_s2, _ = spilled_pass(fast_env)
    spilled_s = min(spilled_s, fast_s2)
    legacy_disk = int(legacy_sd.get("disk_bytes_written", 0))
    fast_disk = int(sd.get("disk_bytes_written", 0))
    return {
        "rows": n,
        "budget": "2MB",
        "join_match": spilled_join == ref_join and lj == ref_join,
        "agg_match": spilled_agg == ref_agg and la == ref_agg,
        "spilled_s": round(spilled_s, 3),
        "in_memory_s": round(in_mem_s, 3),
        "slowdown_x": round(spilled_s / max(in_mem_s, 1e-9), 3),
        "spill_bytes_written": int(sd.get("bytes_written", 0)),
        "spill_bytes_read": int(sd.get("bytes_read", 0)),
        "recursions": int(sd.get("recursions", 0)),
        "depth_exhausted": int(sd.get("depth_exhausted", 0)),
        "agg_buckets_merged": int(sd.get("agg_buckets_merged", 0)),
        "store_peak_bytes": int(sd.get("store_peak_bytes", 0)),
        "legacy": {
            "spilled_s": round(legacy_s, 3),
            "disk_bytes_written": legacy_disk,
            "spill_bytes_written": int(legacy_sd.get("bytes_written", 0)),
        },
        "fast": {
            "spilled_s": round(spilled_s, 3),
            "disk_bytes_written": fast_disk,
            "io_parallelism": 4,
            "compression": "lz4",
        },
        "fast_vs_legacy_wall_x": round(
            legacy_s / max(spilled_s, 1e-9), 3),
        "fast_vs_legacy_disk_ratio": round(
            fast_disk / max(legacy_disk, 1), 3),
    }


def _canon_rows(d: dict):
    """Column dict → sorted row tuples (floats rounded) for an
    order-insensitive answer comparison."""
    cols = sorted(d)
    return sorted(tuple(round(v, 6) if isinstance(v, float) else v
                        for v in row)
                  for row in zip(*(d[c] for c in cols)))


def run_fuzz_smoke() -> int:
    """``--fuzz-smoke``: the plan-discipline CI gate. Runs the
    differential plan fuzzer (seeded random queries; every engine mode
    matrix — optimized / fused / spilled / replanned / combined — must
    answer bit-identically to the unoptimized reference) with the plan
    sanitizer armed, and emits seeds-run / mismatch / sanitizer-
    violation counts. Exit 1 on any mismatch, error, or contract
    violation."""
    os.environ.setdefault("DAFT_TPU_SANITIZE_PLAN", "1")
    from daft_tpu.analysis import plan_fuzzer, plan_sanitizer
    if plan_sanitizer.enabled_by_env() and not plan_sanitizer.is_enabled():
        plan_sanitizer.enable()
    res = plan_fuzzer.run_fuzz(log=print)
    s = res.summary()
    detail = dict(s)
    detail["modes"] = list(plan_fuzzer.MODES)
    for m in res.mismatches:
        print("plan fuzzer MISMATCH\n" + m.repro())
    for e in res.errors:
        print(f"plan fuzzer error: {e}")
    if plan_sanitizer.is_enabled():
        print(plan_sanitizer.report())
    print(json.dumps({"fuzz_smoke": detail}), flush=True)
    ok = not (res.mismatches or res.errors or res.sanitizer_violations)
    print(f"fuzz smoke: {s['seeds_run']} seeds, "
          f"{s['cases_compared']} comparisons, "
          f"{s['mismatches']} mismatches, "
          f"{s['sanitizer_violations']} sanitizer violations -> "
          + ("OK" if ok else "FAIL"))
    return 0 if ok else 1


def run_scale_smoke() -> int:
    """``--scale-smoke``: the out-of-core CI gate. The FULL 22-query
    TPC-H suite at a small SF under a forced-tiny memory limit (every
    join/agg takes the spill path) with the sanitizer on; every answer
    is checked against the unbounded in-memory run. Exit 1 on a wrong
    answer, unbounded RSS (peak past the ceiling), a leaked spill file,
    or a lock-order cycle."""
    import shutil
    import tempfile

    os.environ.setdefault("DAFT_TPU_SANITIZE", "1")
    sf = float(os.environ.get("BENCH_SCALE_SMOKE_SF", "0.1"))
    limit = os.environ.get("BENCH_SCALE_SMOKE_LIMIT", "400KB")
    ceiling = _parse_bytes_env("BENCH_SCALE_SMOKE_RSS_CEILING", 4 << 30)
    budget_s = float(os.environ.get("BENCH_SCALE_SMOKE_BUDGET_S", "900"))
    root = os.path.join(REPO, ".cache", f"tpch_sf{sf}_v2")
    if not os.path.isdir(os.path.join(root, "lineitem")):
        from benchmarking.tpch.datagen import generate_tpch
        print(f"generating TPC-H SF{sf} …", file=sys.stderr, flush=True)
        generate_tpch(root, sf, 4)

    from daft_tpu.execution import governor as gov
    from daft_tpu.execution import memory as mem
    spill_dir = tempfile.mkdtemp(prefix="daft_tpu_scale_smoke_")
    os.environ["DAFT_TPU_SPILL_DIR"] = spill_dir
    mem._spill_dir = None
    gov.reset_peak()
    t0 = time.time()
    mismatches, errors, completed, skipped = [], {}, [], []
    spill_bytes = 0
    try:
        for qn in TPCH_QUERIES:
            if time.time() - t0 > budget_s:
                skipped.append(qn)
                continue
            try:
                ref, _, _ = run_tpch_query(root, qn)
                # FORCED spill: the knobs (not the cost model) pick the
                # out-of-core path, so even a tiny SF exercises it
                forced = {"DAFT_TPU_MEMORY_LIMIT": limit,
                          "DAFT_TPU_SPILL_AGG": "1",
                          "DAFT_TPU_SPILL_JOIN": "1"}
                os.environ.update(forced)
                s0 = mem.spill_counters_snapshot()
                try:
                    got, _, _ = run_tpch_query(root, qn)
                finally:
                    for kk in forced:
                        os.environ.pop(kk, None)
                sd = mem.spill_counters_delta(s0)
                spill_bytes += int(sd.get("bytes_written", 0))
                if _canon_rows(got) != _canon_rows(ref):
                    mismatches.append(qn)
                completed.append(qn)
            except Exception as exc:  # noqa: BLE001
                errors[qn] = str(exc)[:200]
        leaked = []
        for r, _d, fs in os.walk(spill_dir):
            leaked.extend(os.path.join(r, f) for f in fs)
        cycles = 0
        try:
            from daft_tpu.analysis import lock_sanitizer
            if lock_sanitizer.is_enabled():
                cycles = int(lock_sanitizer.counters_snapshot()
                             .get("graph_cycles", 0))
        except Exception:
            pass
        peak = gov.peak_rss_bytes()
        result = {"scale_smoke": {
            "sf": sf, "limit": limit,
            "completed": len(completed), "skipped": skipped,
            "mismatches": mismatches, "errors": errors,
            "spill_bytes_written": spill_bytes,
            "rss_peak_bytes": int(peak), "rss_ceiling_bytes": ceiling,
            "leaked_spill_files": leaked[:5],
            "sanitizer_cycles": cycles,
            "elapsed_s": round(time.time() - t0, 1),
        }}
        print(json.dumps(result), flush=True)
        ok = (not mismatches and not errors and not leaked
              and not cycles and peak <= ceiling and completed
              and spill_bytes > 0)
        return 0 if ok else 1
    finally:
        shutil.rmtree(spill_dir, ignore_errors=True)
        os.environ.pop("DAFT_TPU_SPILL_DIR", None)
        mem._spill_dir = None


def _parse_bytes_env(name: str, default: int) -> int:
    v = os.environ.get(name)
    if not v:
        return default
    from daft_tpu.execution.memory import parse_bytes
    return parse_bytes(v)


def run_adaptive_bench():
    """``--adaptive``: the self-tuning feedback loops on mis-estimated
    data (round 20). Two probes:

    1. **runtime re-planning** — a distributed group-by over NEAR-UNIQUE
       in-memory keys (no cardinality evidence: the static plan
       default-accepts the map-side combine and pays a wasted full agg
       pass per map task); DAFT_TPU_ADAPTIVE measures the keys exactly
       and flips the combine OFF. Static-vs-adaptive wall, identical
       results, decision counters.
    2. **calibrated cost model** — a parquet group-by whose footer NDV
       (int min/max range) over-predicts the true key count >100x, so
       the hard-coded model DECLINES the combine that would collapse
       the wire; one calibrated pass observes the actual/footer ratio
       (NDV_FOOTER_RATIO) and the re-run flips the decision ON —
       wire-row reduction + the decision diff vs the hard-coded
       constants, identical results.
    """
    import numpy as np

    import daft_tpu as dt
    import daft_tpu.context as dctx
    from daft_tpu import col
    from daft_tpu.device import calibration as cal
    from daft_tpu.device import costmodel
    from daft_tpu.distributed import shuffle_service as ss
    from daft_tpu.physical import adaptive
    from daft_tpu.runners.distributed_runner import DistributedRunner

    def one_run(q, env):
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        runner = DistributedRunner(num_workers=3)
        old = dctx.get_context()._runner
        dctx.get_context().set_runner(runner)
        s0 = ss.shuffle_counters_snapshot()
        a0 = adaptive.counters_snapshot()
        t0 = time.time()
        try:
            out = _canon_rows(q())
        finally:
            dctx.get_context().set_runner(old)
            if runner._manager is not None:
                runner._manager.shutdown()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        return (out, time.time() - t0,
                ss.shuffle_counters_delta(s0),
                adaptive.counters_delta(a0))

    # ---- probe 1: runtime re-planning on near-unique in-memory keys.
    # A wide decomposable agg set: the map-side combine the static plan
    # default-accepts re-aggregates EVERY column per partition — the
    # wasted pass the measured-NDV flip avoids scales with it
    n = 800_000
    nu = {"k": np.arange(n).tolist(), "v": np.arange(n).tolist(),
          "w": (np.arange(n) * 3 % 997).tolist(),
          "x": np.arange(n, dtype="float64").tolist()}

    def q_nearuniq():
        return (dt.from_pydict(nu).into_partitions(4)
                .groupby("k").agg(col("v").sum().alias("sv"),
                                  col("w").sum().alias("sw"),
                                  col("x").sum().alias("sx"),
                                  col("v").count().alias("cv"),
                                  col("x").mean().alias("mx"))
                .to_pydict())

    common = {"DAFT_TPU_DEVICE": "0",
              "DAFT_TPU_DISTRIBUTED_SHUFFLE": "flight"}
    one_run(q_nearuniq, {**common, "DAFT_TPU_ADAPTIVE": "0"})  # warm-up
    # min-of-3 per mode: the combine-pass delta must clear run noise
    s_runs, a_runs = [], []
    for _ in range(3):
        s_out, s_wall, s_sh, _ = one_run(
            q_nearuniq, {**common, "DAFT_TPU_ADAPTIVE": "0"})
        s_runs.append(s_wall)
        a_out, a_wall, a_sh, a_cnt = one_run(
            q_nearuniq, {**common, "DAFT_TPU_ADAPTIVE": "1"})
        a_runs.append(a_wall)
    s_best, a_best = min(s_runs), min(a_runs)
    replan = {
        "rows": n,
        "match": a_out == s_out,
        "static_s": round(s_best, 3),
        "adaptive_s": round(a_best, 3),
        "static_runs_s": [round(x, 3) for x in s_runs],
        "adaptive_runs_s": [round(x, 3) for x in a_runs],
        "speedup_x": round(s_best / max(a_best, 1e-9), 3),
        "static_combine_rows_in": int(s_sh.get("combine_rows_in", 0)),
        "adaptive_combine_rows_in": int(a_sh.get("combine_rows_in", 0)),
        "decisions": {k: int(v) for k, v in sorted(a_cnt.items())},
    }

    # ---- probe 2: calibrated NDV ratio flips a footer-mispredicted
    # combine — k has 500 true values spread over a ~5M range, so the
    # footer NDV (min/max range clamped to rows) reads near-unique
    import pyarrow as pa
    import pyarrow.parquet as pq
    import tempfile
    nrows, ndv = 600_000, 500
    d = tempfile.mkdtemp(prefix="daft_tpu_adaptive_bench_")
    k = ((np.arange(nrows) % ndv) * 9973).astype(np.int64)
    for i in range(4):
        sl = slice(i * nrows // 4, (i + 1) * nrows // 4)
        pq.write_table(pa.table({"k": k[sl],
                                 "v": np.arange(nrows)[sl].astype(
                                     "float64")}),
                       os.path.join(d, f"{i}.parquet"))

    def q_footer():
        return (dt.read_parquet(os.path.join(d, "*.parquet"))
                .groupby("k").agg(col("v").sum()).to_pydict())

    cal_dir = tempfile.mkdtemp(prefix="daft_tpu_calibration_")
    cal_env = {**common, "DAFT_TPU_ADAPTIVE": "1",
               "DAFT_TPU_CALIBRATION": "1",
               "DAFT_TPU_CALIBRATION_DIR": cal_dir,
               "DAFT_TPU_CALIBRATION_MIN_SAMPLES": "1"}
    from daft_tpu.context import execution_config_ctx
    with execution_config_ctx(scan_tasks_min_size_bytes=1 << 18,
                              default_morsel_size=4096):
        # discarded warm-up (feedback OFF): jit traces / footer caches
        # are one-time costs that must not skew the warm-vs-warm walls
        one_run(q_footer, {**common, "DAFT_TPU_ADAPTIVE": "0"})
        # first pass: hard-coded constants decline the combine (footer
        # reads near-unique); the run OBSERVES the actual/footer ratio
        f_out, f_wall, f_sh, _ = one_run(q_footer, cal_env)
        static_dec = costmodel.combine_wins_pure(nrows, nrows, 4)
        saved = {k2: os.environ.get(k2) for k2 in cal_env}
        os.environ.update(cal_env)
        try:
            ratio = cal.summary().get("NDV_FOOTER_RATIO", {}).get(
                "value") or 1.0
        finally:
            for k2, v in saved.items():
                if v is None:
                    os.environ.pop(k2, None)
                else:
                    os.environ[k2] = v
        # calibrated re-run: the observed ratio damps the footer
        # evidence and flips the combine ON
        dc0 = dict(costmodel.decision_counts.get("shuffle_combine",
                                                 {"device": 0}))
        c_out, c_wall, c_sh, c_cnt = one_run(q_footer, cal_env)
        dc1 = costmodel.decision_counts.get("shuffle_combine",
                                            {"device": 0})
        calibrated_dec = dc1.get("device", 0) > dc0.get("device", 0)
        # static CONTROL at the same warmth (feedback off — the
        # hard-coded decision): the wall the calibrated re-plan must
        # beat on this mis-estimated data
        g_out, g_wall, _, _ = one_run(
            q_footer, {**common, "DAFT_TPU_ADAPTIVE": "0",
                       "DAFT_TPU_CALIBRATION": "0"})
    calibrated = {
        "rows": nrows, "true_ndv": ndv,
        "footer_ndv_overestimate_x": round(nrows / ndv, 1),
        "match": c_out == f_out,
        "observed_ndv_ratio": round(ratio, 5),
        "static_combine_decision": bool(static_dec),
        "calibrated_combine_decision": calibrated_dec,
        "decision_changed": bool(static_dec) != calibrated_dec,
        "first_pass_s": round(f_wall, 3),
        "calibrated_pass_s": round(c_wall, 3),
        "static_control_s": round(g_wall, 3),
        "static_control_match": g_out == f_out,
        "speedup_x": round(g_wall / max(c_wall, 1e-9), 3),
        "first_combine_rows_out": int(f_sh.get("combine_rows_out", 0)),
        "calibrated_combine_rows_out":
            int(c_sh.get("combine_rows_out", 0)),
        "calibrated_combine_rows_in":
            int(c_sh.get("combine_rows_in", 0)),
        "wire_mbps_observed": round(
            (cal.summary().get("SHUFFLE_WIRE_BPS", {}).get("value")
             or 0.0) / 1e6, 1),
        "decisions": {k2: int(v) for k2, v in sorted(c_cnt.items())},
    }
    # the gate rides the calibrated probe: on footer-mispredicted data
    # the re-planned (calibrated) run must beat the static-decision wall
    # with identical results, AND the calibrated model must have changed
    # a decision vs the hard-coded constants. Probe 1's wall is reported
    # but not gated — one avoided combine pass is real yet small next to
    # run noise on a loaded box.
    return {"replan": replan, "calibrated": calibrated,
            "gate_pass": bool(replan["match"] and calibrated["match"]
                              and calibrated["static_control_match"]
                              and calibrated["speedup_x"] > 1.0
                              and calibrated["decision_changed"])}


def run_shuffle_bench():
    """``--shuffle``: microbench of the distributed shuffle data plane.
    Two probes, both landing in the artifact so the trajectory finally
    captures shuffle throughput:

    1. a TPC-H Q1-shaped distributed group-by (low-cardinality keys,
       sum/mean/count aggs) through the flight shuffle with the fast path
       OFF (no combine, no compression) and ON (defaults) — rows/s through
       the hash exchange, bytes over the wire, compression ratio, combine
       reduction factor;
    2. a multi-source reduce fetch, serial vs the bounded parallel pool —
       the overlap evidence (parallel wall < serial sum).
    """
    import numpy as np

    import daft_tpu as dt
    import daft_tpu.context as dctx
    from daft_tpu import col
    from daft_tpu.distributed import shuffle_service as ss
    from daft_tpu.runners.distributed_runner import DistributedRunner

    rng = np.random.default_rng(8)
    n = 300_000
    data = {
        "rf": rng.integers(0, 3, n).tolist(),
        "ls": rng.integers(0, 2, n).tolist(),
        "qty": rng.integers(1, 50, n).astype("float64").tolist(),
        "price": rng.uniform(1, 100, n).round(2).tolist(),
    }

    def q1_shape(df):
        return (df.groupby("rf", "ls")
                .agg(col("qty").sum().alias("sum_qty"),
                     col("price").sum().alias("sum_price"),
                     col("qty").mean().alias("avg_qty"),
                     col("price").mean().alias("avg_price"),
                     col("qty").count().alias("cnt"))
                .sort("rf").to_pydict())

    def one_run(env):
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        runner = DistributedRunner(num_workers=3)
        old = dctx.get_context()._runner
        dctx.get_context().set_runner(runner)
        before = ss.shuffle_counters_snapshot()
        t0 = time.time()
        try:
            out = q1_shape(dt.from_pydict(data).into_partitions(4))
        finally:
            dctx.get_context().set_runner(old)
            if runner._manager is not None:
                runner._manager.shutdown()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        elapsed = time.time() - t0
        d = ss.shuffle_counters_delta(before)
        return out, elapsed, d

    common = {"DAFT_TPU_DISTRIBUTED_SHUFFLE": "flight",
              "DAFT_TPU_DEVICE": "0"}
    base_out, base_s, base_c = one_run({
        **common, "DAFT_TPU_SHUFFLE_COMBINE": "0",
        "DAFT_TPU_SHUFFLE_COMPRESSION": "none"})
    fast_out, fast_s, fast_c = one_run({
        **common, "DAFT_TPU_SHUFFLE_COMBINE": "auto",
        "DAFT_TPU_SHUFFLE_COMPRESSION": "lz4"})

    def wire(c):
        return int(c.get("bytes_written", 0))

    res = {
        "rows": n,
        "baseline": {  # pre-PR data plane: raw rows, uncompressed, serial
            "elapsed_s": round(base_s, 3),
            "rows_per_s": round(n / base_s, 1),
            "wire_bytes": wire(base_c),
            "rows_on_wire": int(base_c.get("rows_pushed", 0)),
        },
        "fast_path": {
            "elapsed_s": round(fast_s, 3),
            "rows_per_s": round(n / fast_s, 1),
            "wire_bytes": wire(fast_c),
            "rows_on_wire": int(fast_c.get("rows_pushed", 0)),
            "compression_ratio": round(
                fast_c.get("bytes_pushed_raw", 0)
                / max(wire(fast_c), 1), 3),
            "combine_reduction": round(
                fast_c.get("combine_rows_in", 0)
                / max(fast_c.get("combine_rows_out", 1), 1), 2),
            "fetch_wall_s": round(fast_c.get("fetch_span_us", 0) / 1e6, 4),
            "fetch_serial_equiv_s": round(
                fast_c.get("fetch_wall_us", 0) / 1e6, 4),
        },
        "wire_bytes_saved_ratio": round(
            wire(base_c) / max(wire(fast_c), 1), 2),
        # canonicalized: the query sorts by rf only, so tie order among
        # equal-rf groups is unspecified across the two runs
        "answers_match": _canon_rows(base_out) == _canon_rows(fast_out),
    }

    # probe 2: multi-source fetch overlap, serial loop vs the bounded pool
    import pyarrow as pa

    from daft_tpu.distributed.worker import FetchSpec, _ParallelFetch
    srv = ss.make_shuffle_server()
    caches = []
    big = pa.table({"x": np.arange(400_000, dtype=np.int64),
                    "y": rng.uniform(size=400_000)})
    for _ in range(6):
        c = ss.ShuffleCache()
        c.push(0, big)
        srv.register(c)
        caches.append(c)
    srcs = [(srv.address, c.shuffle_id) for c in caches]
    # discarded warm-up pass: both timed measurements below run against
    # warm page cache + warm server threads, so the speedup isolates
    # fetch OVERLAP rather than cache warmth
    for addr, sid in srcs:
        ss.fetch_partition(addr, sid, 0)
    t0 = time.time()
    for addr, sid in srcs:
        ss.fetch_partition(addr, sid, 0)
    serial_s = time.time() - t0
    t0 = time.time()
    parts = list(_ParallelFetch(FetchSpec(srcs, 0)))
    parallel_s = time.time() - t0
    for c in caches:
        srv.unregister(c.shuffle_id)
    srv.shutdown()
    res["fetch_overlap"] = {
        "sources": len(srcs),
        "bytes_per_source": int(big.nbytes),
        "serial_s": round(serial_s, 4),
        "parallel_s": round(parallel_s, 4),
        "speedup": round(serial_s / max(parallel_s, 1e-9), 2),
        "rows_fetched": sum(len(p) for p in parts),
    }

    # probe 3: codec spill/wire sizes on a real-size payload (the Q1
    # probe's wire tables are tiny combined group states where IPC
    # framing dominates and a ratio would mislead)
    comp = {}
    for codec in ("none", "lz4", "zstd"):
        saved = os.environ.get("DAFT_TPU_SHUFFLE_COMPRESSION")
        os.environ["DAFT_TPU_SHUFFLE_COMPRESSION"] = codec
        try:
            c = ss.ShuffleCache()
            c.push(0, big)
            c.close()
            comp[codec] = c.partition_size(0)
            c.cleanup()
        finally:
            if saved is None:
                os.environ.pop("DAFT_TPU_SHUFFLE_COMPRESSION", None)
            else:
                os.environ["DAFT_TPU_SHUFFLE_COMPRESSION"] = saved
    res["compression_bytes"] = comp
    if comp.get("none"):
        res["compression_ratio_lz4"] = round(
            comp["none"] / max(comp.get("lz4", 1), 1), 3)
    return res


def _mesh_exchange_child():
    """``--mesh-exchange-child``: one cold process (the parent sets
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` so the
    virtual pod mesh exists) driving ONE hash-repartition boundary
    through the distributed stage runner on the exchange path named by
    ``DAFT_TPU_EXCHANGE_PATH``. Prints one JSON line: warm elapsed,
    rows/s, the shuffle-plane counter delta (bytes per link: ici vs
    wire, stream counts, path decisions), and an order-insensitive
    row-set checksum for the parity gate."""
    import hashlib
    import shutil
    import tempfile

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import daft_tpu as dt
    import daft_tpu.context as dctx
    from daft_tpu import col
    from daft_tpu.distributed import shuffle_service as ss
    from daft_tpu.runners.distributed_runner import DistributedRunner

    n = int(os.environ.get("BENCH_MESH_ROWS", "400000"))
    nparts = 8  # == the virtual pod's mesh width
    nfiles = 8  # one scan task per file → map tasks shard over workers
    rng = np.random.default_rng(17)
    root = tempfile.mkdtemp(prefix="daft_tpu_meshbench_")
    per = n // nfiles
    for i in range(nfiles):
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 1 << 20, per)),
            "v": pa.array(rng.integers(0, 1 << 30, per)),
            "w": pa.array(rng.integers(0, 1 << 30, per)),
        }), os.path.join(root, f"part-{i}.parquet"))

    def q():
        df = dt.read_parquet(os.path.join(root, "*.parquet"))
        return df.repartition(nparts, col("k")).to_arrow()

    def checksum(t: "pa.Table"):
        arr = np.stack([t.column(c).to_numpy().astype(np.int64)
                        for c in ("k", "v", "w")], axis=1)
        arr = arr[np.lexsort(arr.T[::-1])]
        return hashlib.sha256(np.ascontiguousarray(arr).tobytes()) \
            .hexdigest()

    runner = DistributedRunner(num_workers=4)
    old = dctx.get_context()._runner
    dctx.get_context().set_runner(runner)
    try:
        q()  # warm-up: compiles, server boot, page cache, trace cache
        before = ss.shuffle_counters_snapshot()
        t0 = time.time()
        out = q()
        elapsed = time.time() - t0
        delta = ss.shuffle_counters_delta(before)
    finally:
        dctx.get_context().set_runner(old)
        if runner._manager is not None:
            runner._manager.shutdown()
        shutil.rmtree(root, ignore_errors=True)
    counters = {k: int(v) for k, v in sorted(delta.items())
                if k in ("ici_bytes", "ici_rows", "ici_exchanges",
                         "bytes_written", "bytes_fetched", "fetches",
                         "streams_registered", "hierarchical_streams",
                         "rows_pushed")
                or k.startswith("exchange_path_")}
    print(json.dumps({
        "path": os.environ.get("DAFT_TPU_EXCHANGE_PATH", "auto"),
        "rows": n,
        "partitions": nparts,
        "elapsed_s": round(elapsed, 4),
        "rows_per_s": round(n / elapsed, 1),
        "counters": counters,
        "checksum": checksum(out),
    }))


def run_mesh_exchange_bench():
    """``--shuffle`` family 2: the pod-native exchange ladder on a
    simulated multi-device pod (8 virtual CPU devices). One identical
    hash boundary (400k rows × 24 B into 8 partitions, 4 workers) runs
    per rung in a cold child process:

    - ``flight``       — per-worker map streams over the socket (today);
    - ``collective``   — the boundary rides the mesh all_to_all, zero
      Flight streams (admission forced so the virtual mesh is used);
    - ``hierarchical`` — workers split across two simulated pods; each
      pod exchanges intra-mesh and serves ONE stream per mesh.

    The artifact carries rows/s per rung, bytes per LINK (ici vs wire),
    stream counts (the hierarchical claim: streams == meshes, not
    workers), and the bit-parity verdict from the row-set checksums."""
    mesh_flags = "--xla_force_host_platform_device_count=8"

    def child(path, extra):
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": mesh_flags,
               # one scan task per file: map tasks really shard across
               # the 4 workers (flight registers one stream per task)
               "DAFT_SCAN_TASKS_MIN_SIZE_BYTES": "1",
               "DAFT_TPU_EXCHANGE_PATH": path, **extra}
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--mesh-exchange-child"],
            capture_output=True, text=True, timeout=420, cwd=REPO,
            env=env)
        merged = _merge_lines(proc.stdout or "")
        if merged is None:
            raise RuntimeError(
                f"mesh-exchange child ({path}) rc={proc.returncode}: "
                f"{(proc.stderr or '')[-500:]}")
        return merged

    flight = child("flight", {"DAFT_TPU_DEVICE": "0"})
    collective = child("collective", {"DAFT_TPU_DEVICE": "1",
                                      "DAFT_TPU_MESH_MIN_ROWS": "0"})
    hier = child("hierarchical", {
        "DAFT_TPU_DEVICE": "1", "DAFT_TPU_MESH_MIN_ROWS": "0",
        "DAFT_TPU_WORKER_TOPOLOGY":
            "podA=worker-0,worker-1;podB=worker-2,worker-3"})
    out = {"flight": flight, "collective": collective,
           "hierarchical": hier}
    out["parity"] = {
        "collective": collective["checksum"] == flight["checksum"],
        "hierarchical": hier["checksum"] == flight["checksum"]}
    out["collective_speedup_vs_flight"] = round(
        flight["elapsed_s"] / max(collective["elapsed_s"], 1e-9), 2)
    out["hierarchical_speedup_vs_flight"] = round(
        flight["elapsed_s"] / max(hier["elapsed_s"], 1e-9), 2)
    # the stream-count claim: flight registers one stream per map task,
    # hierarchical one per mesh
    out["streams"] = {
        "flight": flight["counters"].get("streams_registered", 0),
        "hierarchical": hier["counters"].get("streams_registered", 0),
        "meshes": 2}
    # bytes per link: what rode ICI instead of the wire
    out["bytes_per_link"] = {
        "flight_wire": flight["counters"].get("bytes_written", 0),
        "collective_ici": collective["counters"].get("ici_bytes", 0),
        "collective_wire": collective["counters"].get("bytes_written", 0),
        "hierarchical_ici": hier["counters"].get("ici_bytes", 0),
        "hierarchical_wire": hier["counters"].get("bytes_written", 0)}
    return out


def run_scan_bench():
    """``--scan``: microbench of the scan-side IO plane against a
    latency-injected local HTTP object store (every request pays a fixed
    service delay, modeling object-store RTT). One projected, filtered
    multi-file parquet read runs twice: the pre-PR path
    (``DAFT_TPU_IO_PLANNED_READS=0`` + ``DAFT_TPU_SCAN_PREFETCH=0`` —
    per-column-chunk ranged GETs, whole-task loads) and the fast path
    (defaults: planned coalesced ranges, parallel fetch,
    prefetch-pipelined tasks). Records GET-request reduction, scan
    wall-clock speedup, answer parity, and the per-query ``io`` stats
    block."""
    import http.server
    import shutil
    import tempfile
    import threading

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import daft_tpu as dt
    import daft_tpu.observability as obs
    from daft_tpu import col
    from daft_tpu.io import read_planner as rp

    delay_s = float(os.environ.get("BENCH_SCAN_DELAY_MS", "15")) / 1e3
    nfiles, rows = 8, 160_000
    root = tempfile.mkdtemp(prefix="daft_tpu_scanbench_")
    rng = np.random.default_rng(9)
    for i in range(nfiles):
        t = pa.table({
            "seq": pa.array(np.arange(i * rows, (i + 1) * rows)),
            "k": pa.array(rng.integers(0, 1000, rows)),
            "v": pa.array(rng.uniform(size=rows)),
            "w": pa.array(rng.uniform(size=rows)),
            "pad_f": pa.array(rng.uniform(size=rows)),
            "pad_s": pa.array([f"pad-{j % 97:04d}" for j in range(rows)]),
        })
        pq.write_table(t, os.path.join(root, f"part-{i}.parquet"),
                       row_group_size=rows // 8)

    class _Store(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _file(self):
            p = os.path.join(root, self.path.lstrip("/"))
            return p if os.path.isfile(p) else None

        def do_HEAD(self):
            time.sleep(delay_s)
            p = self._file()
            if p is None:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(os.path.getsize(p)))
            self.end_headers()

        def do_GET(self):
            time.sleep(delay_s)
            p = self._file()
            if p is None:
                self.send_response(404)
                self.end_headers()
                return
            with open(p, "rb") as f:
                data = f.read()
            rng_hdr = self.headers.get("Range")
            if rng_hdr:
                spec = rng_hdr.split("=")[1]
                a, b = spec.split("-")
                start, end = int(a), min(int(b), len(data) - 1)
                chunk = data[start:end + 1]
                self.send_response(206)
            else:
                chunk = data
                self.send_response(200)
            self.send_header("Content-Length", str(len(chunk)))
            self.end_headers()
            self.wfile.write(chunk)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Store)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    urls = [f"http://127.0.0.1:{srv.server_port}/part-{i}.parquet"
            for i in range(nfiles)]
    half = nfiles * rows // 2  # ordered seq → half the row groups prune

    def query():
        return (dt.read_parquet(urls)
                .where(col("seq") < half)
                .select("k", "v")
                .sum("v").to_pydict())

    def one_run(env):
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        before = rp.scan_counters_snapshot()
        t0 = time.time()
        try:
            out = query()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        elapsed = time.time() - t0
        return out, elapsed, rp.scan_counters_delta(before)

    try:
        # both runs pin their knobs via env (the context may have frozen
        # either set into its config at first touch; env always wins)
        naive_out, naive_s, naive_c = one_run(
            {"DAFT_TPU_IO_PLANNED_READS": "0", "DAFT_TPU_SCAN_PREFETCH": "0"})
        fast_out, fast_s, fast_c = one_run(
            {"DAFT_TPU_IO_PLANNED_READS": "1", "DAFT_TPU_SCAN_PREFETCH": "2"})
    finally:
        srv.shutdown()
        shutil.rmtree(root, ignore_errors=True)

    st = obs.last_query_stats()
    res = {
        "files": nfiles, "rows": nfiles * rows,
        "rows_scanned": half,
        "request_delay_ms": delay_s * 1e3,
        "naive": {
            "elapsed_s": round(naive_s, 3),
            "rows_per_s": round(half / naive_s, 1),
            "gets": int(naive_c.get("gets", 0)),
            "bytes_fetched": int(naive_c.get("bytes_fetched", 0)),
        },
        "fast_path": {
            "elapsed_s": round(fast_s, 3),
            "rows_per_s": round(half / fast_s, 1),
            "gets": int(fast_c.get("gets", 0)),
            "bytes_fetched": int(fast_c.get("bytes_fetched", 0)),
            "ranges_planned": int(fast_c.get("ranges_planned", 0)),
            "range_requests": int(fast_c.get("range_requests", 0)),
            "bytes_used": int(fast_c.get("bytes_used", 0)),
            "prefetch_wall_s": round(fast_c.get("scan_span_us", 0) / 1e6, 4),
            "prefetch_serial_equiv_s": round(
                fast_c.get("scan_task_us", 0) / 1e6, 4),
        },
        "request_reduction": round(
            naive_c.get("gets", 0) / max(fast_c.get("gets", 1), 1), 2),
        "scan_speedup": round(naive_s / max(fast_s, 1e-9), 2),
        "answers_match": _canon_rows(naive_out) == _canon_rows(fast_out),
        # the io stats block explain(analyze=True) renders for this query
        "io_stats_block": obs.render_io_block(st.io) if st is not None
        else None,
    }
    return res


def _pct(sorted_vals, p: float):
    """p-quantile of a pre-sorted list (nearest-rank)."""
    if not sorted_vals:
        return None
    i = min(int(p * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def run_serve_bench(root=None, duration_s=None, concurrency=None):
    """``--serve``: sustained mixed traffic through the query scheduler.

    Closed-loop clients (one per worker slot, 3 sessions) submit a
    rotating mix of TPC-H shapes (q1/q6/q3) + point lookups for
    ``BENCH_SERVE_SECONDS`` (default 20s) at ``BENCH_SERVE_CONCURRENCY``
    (default 4). Reports QPS, p50/p99 latency, queue wait, admission
    rejections, plan/result cache hit rates, the repeated-vs-cold mean
    latency ratio (the plan/result caches' amortization evidence), and
    the admission-accounting leak check (outstanding admitted bytes must
    return to zero after drain)."""
    import threading

    from benchmarking.tpch import queries as Q

    from daft_tpu import col, serving

    if root is None:
        # serving traffic is interactive-shaped: a dedicated small TPC-H
        # dataset (SF0.1) keeps per-query latency in the hundreds of ms
        # so a bounded run actually exercises repeats, queuing, and the
        # caches (SF1 queries run ~15s+ on this class of box — a 20s
        # window would barely complete one per worker)
        root = os.path.join(REPO, ".cache", "tpch_sf0.1_serve_v1")
        if not os.path.isdir(os.path.join(root, "lineitem")):
            from benchmarking.tpch.datagen import generate_tpch
            print("generating TPC-H SF0.1 (serve bench, one-time) …",
                  file=sys.stderr, flush=True)
            generate_tpch(root, 0.1, 2)
    duration_s = duration_s if duration_s is not None \
        else float(os.environ.get("BENCH_SERVE_SECONDS", "20"))
    concurrency = concurrency if concurrency is not None \
        else int(os.environ.get("BENCH_SERVE_CONCURRENCY", "4"))
    get_df = _get_df_factory(root)

    def lookup(k):
        return get_df("lineitem").where(col("l_orderkey") == k) \
            .select("l_orderkey", "l_partkey", "l_quantity",
                    "l_extendedprice").limit(10)

    shapes = [("q1", lambda: Q.q1(get_df)),
              ("q6", lambda: Q.q6(get_df)),
              ("q3", lambda: Q.q3(get_df))] + \
             [(f"lookup{k}", (lambda k=k: lookup(k)))
              for k in (1, 7, 32, 69)]
    sched = serving.QueryScheduler(concurrency=concurrency)
    recs = []
    rec_lock = threading.Lock()
    submit_counts = {}
    t_end = time.time() + duration_s

    def client(ci):
        i = ci
        while time.time() < t_end:
            name, fac = shapes[i % len(shapes)]
            i += concurrency
            with rec_lock:
                n_prior = submit_counts.get(name, 0)
                submit_counts[name] = n_prior + 1
            t0 = time.time()
            try:
                h = sched.submit(fac(), session=f"s{ci % 3}")
                h.result(timeout=120)
            except serving.AdmissionRejected as exc:
                with rec_lock:
                    recs.append((name, None, None, False,
                                 f"rejected:{exc.kind}"))
                continue
            except Exception as exc:  # noqa: BLE001 — recorded, not fatal
                with rec_lock:
                    recs.append((name, None, None, False,
                                 f"error:{str(exc)[:80]}"))
                continue
            with rec_lock:
                recs.append((name, time.time() - t0, h.queue_wait_s,
                             n_prior == 0, "ok"))

    t_wall0 = time.time()
    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 150)
    wall = time.time() - t_wall0
    sched_counters = sched.counters_snapshot()
    outstanding = sched.admission.outstanding
    sched.shutdown()

    ok = [r for r in recs if r[4] == "ok"]
    lats = sorted(r[1] for r in ok)
    waits = sorted(r[2] for r in ok)
    cold = [r[1] for r in ok if r[3]]
    warm = [r[1] for r in ok if not r[3]]
    errors = [r[4] for r in recs if r[4].startswith("error")]
    pc_hits = sched_counters.get("plan_cache_hits", 0)
    pc_miss = sched_counters.get("plan_cache_misses", 0)
    rc_hits = sched_counters.get("result_cache_hits", 0)
    rc_miss = sched_counters.get("result_cache_misses", 0)
    out = {
        "concurrency": concurrency,
        "duration_s": round(wall, 2),
        "completed": len(ok),
        "qps": round(len(ok) / max(wall, 1e-9), 2),
        "latency_p50_ms": round(1e3 * (_pct(lats, 0.50) or 0), 2),
        "latency_p99_ms": round(1e3 * (_pct(lats, 0.99) or 0), 2),
        "queue_wait_mean_ms": round(
            1e3 * (sum(waits) / len(waits) if waits else 0), 2),
        "queue_wait_p99_ms": round(1e3 * (_pct(waits, 0.99) or 0), 2),
        "rejections": {
            k.replace("rejected_", ""): int(v)
            for k, v in sched_counters.items()
            if k.startswith("rejected_") and v},
        "plan_cache_hit_rate": round(
            pc_hits / max(pc_hits + pc_miss, 1), 3),
        "result_cache_hit_rate": round(
            rc_hits / max(rc_hits + rc_miss, 1), 3),
        "plan_cache_structure_hits": int(
            sched_counters.get("plan_cache_structure_hits", 0)),
        "cold_mean_ms": round(
            1e3 * sum(cold) / len(cold), 2) if cold else None,
        "repeat_mean_ms": round(
            1e3 * sum(warm) / len(warm), 2) if warm else None,
        "admitted_bytes_outstanding_after_drain": int(outstanding),
    }
    if cold and warm and sum(warm):
        out["repeat_speedup"] = round(
            (sum(cold) / len(cold)) / (sum(warm) / len(warm)), 2)
    try:
        from daft_tpu.device.runtime import compile_cache_counters
        out["jit_projection_cache"] = compile_cache_counters()
    except Exception:
        pass
    try:
        from daft_tpu.analysis import lock_sanitizer
        if lock_sanitizer.is_enabled():
            out["sanitizer_cycles"] = int(
                lock_sanitizer.counters_snapshot().get("graph_cycles", 0))
    except Exception:
        pass
    if errors:
        out["errors"] = errors[:5]
        out["n_errors"] = len(errors)
    return out


def run_serve_smoke() -> int:
    """``--serve-smoke``: the CI gate. A few seconds of mixed traffic over
    a small temp table; exit 1 on an admission-accounting leak
    (outstanding admitted bytes after drain), a wrong answer, or any
    lock-order sanitizer cycle. No TPC-H datagen required."""
    import shutil
    import tempfile

    import daft_tpu as dt
    from daft_tpu import col

    d = tempfile.mkdtemp(prefix="daft_tpu_serve_smoke_")
    try:
        n = 4000
        dt.from_pydict({
            "k": list(range(n)),
            "g": [i % 13 for i in range(n)],
            "v": [float(i % 97) for i in range(n)],
        }).write_parquet(os.path.join(d, "t"))
        root_glob = os.path.join(d, "t", "*.parquet")

        def table():
            return dt.read_parquet(root_glob)

        expected = table().groupby("g") \
            .agg(col("v").sum().alias("s")).sort("g").to_pydict()

        import threading

        from daft_tpu import serving
        shapes = [
            ("agg", lambda: table().groupby("g")
             .agg(col("v").sum().alias("s")).sort("g")),
            ("topk", lambda: table().sort("v", desc=True).limit(5)),
            ("lookup", lambda: table().where(col("k") == 1234).limit(1)),
        ]
        sched = serving.QueryScheduler(concurrency=4)
        t_end = time.time() + float(
            os.environ.get("BENCH_SERVE_SMOKE_SECONDS", "4"))
        failures = []
        done = [0]
        lock = threading.Lock()

        def client(ci):
            i = ci
            while time.time() < t_end:
                name, fac = shapes[i % len(shapes)]
                i += 1
                try:
                    h = sched.submit(fac(), session=f"s{ci % 3}")
                    ps = h.result(timeout=60)
                    if name == "agg":
                        got = ps.to_recordbatch().to_pydict()
                        if got != expected:
                            raise AssertionError(
                                "agg answer mismatch under concurrency")
                    with lock:
                        done[0] += 1
                except Exception as exc:  # noqa: BLE001
                    with lock:
                        failures.append(f"{name}: {exc!r}"[:200])

        threads = [threading.Thread(target=client, args=(ci,), daemon=True)
                   for ci in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        outstanding = sched.admission.outstanding
        counters = sched.counters_snapshot()
        sched.shutdown()
        cycles = 0
        try:
            from daft_tpu.analysis import lock_sanitizer
            if lock_sanitizer.is_enabled():
                cycles = int(lock_sanitizer.counters_snapshot()
                             .get("graph_cycles", 0))
        except Exception:
            pass
        result = {
            "serve_smoke": {
                "completed": done[0],
                "failures": failures[:5],
                "admitted_bytes_outstanding": int(outstanding),
                "sanitizer_cycles": cycles,
                "plan_cache_hits": int(counters.get("plan_cache_hits", 0)),
                "result_cache_hits": int(
                    counters.get("result_cache_hits", 0)),
            }}
        print(json.dumps(result), flush=True)
        if failures or outstanding or cycles or done[0] == 0:
            return 1
        return 0
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _fleet_make_table(prefix: str, n: int = 20000):
    """Temp parquet table for fleet traffic; returns (dir, glob)."""
    import tempfile

    import daft_tpu as dt
    d = tempfile.mkdtemp(prefix=prefix)
    dt.from_pydict({
        "k": list(range(n)),
        "g": [i % 13 for i in range(n)],
        "v": [float(i % 97) for i in range(n)],
    }).write_parquet(os.path.join(d, "t"))
    return d, os.path.join(d, "t", "*.parquet")


class _LatencyFileServer:
    """Serves ONE local file under every requested path, with a fixed
    per-request sleep — object-store GET latency emulation for the fleet
    bench. Distinct object names behave like distinct partitions in a
    bucket (path-keyed caches miss), and the sleep happens server-side
    in a blocked thread, so on a small CI host aggregate throughput is
    bounded by the fleet's admission slots × storage latency — the
    serving-capacity quantity the replica count actually scales — not by
    this host's core count."""

    def __init__(self, file_path: str, latency_s: float = 0.1):
        with open(file_path, "rb") as f:
            self.data = f.read()
        self.latency_s = latency_s
        self._httpd = None

    def start(self) -> str:
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _serve(self, head_only: bool):
                time.sleep(srv.latency_s)
                body = srv.data
                code = 200
                rng = self.headers.get("Range")
                if rng and rng.startswith("bytes="):
                    a, _, b = rng[len("bytes="):].partition("-")
                    start = int(a or 0)
                    end = min(int(b) + 1 if b else len(body), len(body))
                    body, code = body[start:end], 206
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                # a stable ETag is the version signal that lets the
                # serving caches key remote-sourced plans (fingerprint
                # sources = size + etag, like a real object store)
                self.send_header("ETag", f'"bench-{len(srv.data)}"')
                self.end_headers()
                if not head_only:
                    self.wfile.write(body)

            def do_GET(self):
                try:
                    self._serve(head_only=False)
                except Exception:
                    pass

            def do_HEAD(self):
                try:
                    self._serve(head_only=True)
                except Exception:
                    pass

        import threading
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._httpd.daemon_threads = True
        t = threading.Thread(target=self._httpd.serve_forever,
                             daemon=True)
        t.start()
        return f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def _fleet_shapes(source, n_rows: int = 4000, heavy: bool = False,
                  label: str = ""):
    """SQL traffic mix. Default (smoke): ``source`` is a local glob; two
    repeat shapes (cacheable) + a rotating parameterized lookup whose
    25-literal cycle wraps, so the result cache dominates. Heavy
    (bench): ``source`` is a :class:`_LatencyFileServer` base URL; one
    repeat shape on a fixed object + two effectively-unique windowed
    aggregations per round, each scanning a DISTINCT object name — every
    miss pays real object-store GET latency, which is what makes
    aggregate QPS scale with replica count."""
    if heavy:
        agg = (f"SELECT g, sum(v) AS s FROM "
               f"read_parquet('{source}/hot.parquet') "
               "GROUP BY g ORDER BY g")

        def shape(i):
            if i % 3 == 0:
                return "agg", agg
            off = (i * 7919) % max(n_rows - 2000, 1)
            return "window", (
                f"SELECT g, sum(v) AS s, count(v) AS c FROM "
                f"read_parquet('{source}/w{label}-{off}.parquet') "
                f"WHERE k >= {off} AND k < {off + 2000} "
                "GROUP BY g ORDER BY g")
        return shape, agg

    agg = (f"SELECT g, sum(v) AS s FROM read_parquet('{source}') "
           "GROUP BY g ORDER BY g")
    topk = (f"SELECT k, v FROM read_parquet('{source}') "
            "ORDER BY v DESC, k LIMIT 5")

    def shape(i):
        j = i % 3
        if j == 0:
            return "agg", agg
        if j == 1:
            return "topk", topk
        kk = (i // 3) % 25
        return "lookup", (f"SELECT k, v FROM read_parquet('{source}') "
                          f"WHERE k = {kk * 37} LIMIT 5")
    return shape, agg


def _agg_matches(data, expected) -> bool:
    """Float-tolerant pydict comparison: group keys must match exactly,
    sums within 1e-6 relative (partial-sum order differs per process)."""
    try:
        if list(data.get("g", [])) != list(expected.get("g", [])):
            return False
        a, b = data.get("s", []), expected.get("s", [])
        if len(a) != len(b):
            return False
        return all(abs(float(x) - float(y))
                   <= 1e-6 * max(1.0, abs(float(y)))
                   for x, y in zip(a, b))
    except Exception:
        return False


def _fleet_traffic(router, glob, duration_s, n_clients, label,
                   expected_agg=None, n_rows: int = 4000,
                   heavy: bool = False):
    """Closed-loop SQL traffic through the router; returns the traffic
    summary (qps, latency percentiles, cache-outcome mix, failures)."""
    import threading
    shape, _agg_sql = _fleet_shapes(glob, n_rows=n_rows, heavy=heavy,
                                    label=label)
    recs = []
    failures = []
    lock = threading.Lock()
    t_end = time.time() + duration_s

    def client(ci):
        i = ci
        while time.time() < t_end:
            name, sql = shape(i)
            i += n_clients
            t0 = time.time()
            try:
                out = router.sql(sql, session=f"{label}-s{ci}",
                                 timeout_s=120.0)
            except Exception as exc:  # noqa: BLE001 — recorded, not fatal
                with lock:
                    failures.append(f"{name}: {exc!r}"[:160])
                continue
            lat = time.time() - t0
            if name == "agg" and expected_agg is not None \
                    and not _agg_matches(out.get("data") or {},
                                         expected_agg):
                with lock:
                    failures.append("agg answer mismatch")
                continue
            with lock:
                recs.append(
                    (lat, (out.get("serving") or {}).get("result_cache"),
                     name))

    t0 = time.time()
    threads = [threading.Thread(target=client, args=(ci,), daemon=True)
               for ci in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 150)
    wall = time.time() - t0
    lats = sorted(r[0] for r in recs)
    outcomes = [r[1] for r in recs]
    hits = sum(1 for o in outcomes if o in ("hit", "fleet_hit"))
    misses = sum(1 for o in outcomes if o == "miss")
    # hit rate restricted to the REPEAT shape — the apples-to-apples
    # "does the fleet cache what one process caches" number, independent
    # of how many unique-miss shapes the mix carries
    hot = [o for _, o, n in recs if n == "agg"]
    hot_hits = sum(1 for o in hot if o in ("hit", "fleet_hit"))
    hot_misses = sum(1 for o in hot if o == "miss")
    return {
        "completed": len(recs),
        "qps": round(len(recs) / max(wall, 1e-9), 2),
        "latency_p50_ms": round(1e3 * (_pct(lats, 0.50) or 0), 2),
        "latency_p99_ms": round(1e3 * (_pct(lats, 0.99) or 0), 2),
        "result_cache_hit_rate": round(hits / max(hits + misses, 1), 3),
        "hot_shape_hit_rate": round(
            hot_hits / max(hot_hits + hot_misses, 1), 3),
        "fleet_hits": sum(1 for o in outcomes if o == "fleet_hit"),
        "failures": failures[:5],
        "n_failures": len(failures),
    }


def run_fleet_bench():
    """``--fleet``: 1 vs 3 subprocess driver replicas under identical
    closed-loop SQL traffic (grpc-free control-plane path). Reports the
    aggregate-QPS scaling factor, the fleet result-cache hit rate vs the
    single-replica run, and the cold-replica warm-start evidence (a 4th
    replica added after the fact answers its FIRST query from the fleet
    cache tier and inherits the gossiped state store)."""
    import shutil
    import threading

    from daft_tpu.fleet.cache_tier import CacheSidecar
    from daft_tpu.fleet.router import FleetRouter, SubprocessReplica

    duration_s = float(os.environ.get("BENCH_FLEET_SECONDS", "12"))
    # closed-loop client count must exceed (fleet slots × full latency /
    # exec latency) or the single replica never saturates its admission
    # slots and the ratio measures client count, not capacity
    n_clients = int(os.environ.get("BENCH_FLEET_CLIENTS", "36"))
    n_rows = int(os.environ.get("BENCH_FLEET_ROWS", "4000"))
    get_ms = float(os.environ.get("BENCH_FLEET_GET_MS", "150"))
    d, local_glob = _fleet_make_table("daft_tpu_fleet_bench_", n=n_rows)
    import glob as globmod
    pq_file = sorted(globmod.glob(local_glob))[0]
    store = _LatencyFileServer(pq_file, latency_s=get_ms / 1e3)
    base = store.start()
    out = {"duration_s": duration_s, "clients": n_clients,
           "rows": n_rows, "emulated_get_ms": get_ms}
    sidecar = CacheSidecar(budget_bytes=256 << 20)
    addr = sidecar.start()
    env = {"DAFT_TPU_FLEET_SIDECAR": addr, "DAFT_TPU_CALIBRATION": "1"}
    _shape, agg_sql = _fleet_shapes(base, n_rows=n_rows, heavy=True)
    try:
        # ---- phase 1: one replica (same sidecar, same env) ----------
        solo = SubprocessReplica.spawn("solo", env=env)
        router1 = FleetRouter([solo])
        _fleet_traffic(router1, base, min(3.0, duration_s), n_clients,
                       "warm", n_rows=n_rows, heavy=True)  # jit warm-up
        out["single"] = _fleet_traffic(router1, base, duration_s,
                                       n_clients, "single",
                                       n_rows=n_rows, heavy=True)
        solo.shutdown()
        # the sidecar keeps phase-1 results; phase 2 uses distinct
        # sessions but identical shapes — which is exactly the fleet
        # tier's job, so count those hits rather than hiding them
        # ---- phase 2: three replicas + gossip -----------------------
        reps = [SubprocessReplica.spawn(f"r{i}", env=env)
                for i in range(3)]
        router3 = FleetRouter(reps)
        stop_gossip = threading.Event()

        def gossip_loop():
            while not stop_gossip.wait(1.0):
                try:
                    router3.gossip_round()
                except Exception:
                    pass

        gt = threading.Thread(target=gossip_loop, daemon=True)
        gt.start()
        _fleet_traffic(router3, base, min(3.0, duration_s), n_clients,
                       "fwarm", n_rows=n_rows, heavy=True)  # per-replica
        out["fleet3"] = _fleet_traffic(router3, base, duration_s,
                                       n_clients, "fleet",
                                       n_rows=n_rows, heavy=True)
        out["fleet3"]["replicas"] = 3
        if out["single"]["qps"]:
            out["scaling_x"] = round(
                out["fleet3"]["qps"] / out["single"]["qps"], 2)
        # ---- phase 3: cold replica inherits fleet state -------------
        cold = SubprocessReplica.spawn("cold", env=env)
        router3.add_replica(cold)
        router3.gossip_round()  # cold pulls the union of fleet history
        inherited = len(cold.state_snapshot().get("origins") or {}) - 1
        t0 = time.time()
        first = cold.sql(agg_sql, session="cold-probe", timeout_s=120.0)
        first_ms = round(1e3 * (time.time() - t0), 2)
        # replay one EXACT window query a warm replica already ran: same
        # fingerprint history key, so a blind admission estimate must
        # seed from the gossiped fleet history instead of the default
        shape_fleet, _ = _fleet_shapes(base, n_rows=n_rows, heavy=True,
                                       label="fleet")
        cold.sql(shape_fleet(1)[1], session="cold-probe", timeout_s=120.0)
        counters = cold.counters()
        state = cold.state_snapshot().get("origins") or {}
        out["cold_replica"] = {
            "origins_inherited": inherited,
            "admission_history_inherited": sum(
                len((s or {}).get("admission") or {})
                for o, s in state.items() if o != "cold"),
            "calibration_inherited": sum(
                len((s or {}).get("calib") or {})
                for o, s in state.items() if o != "cold"),
            "first_query_result_cache":
                (first.get("serving") or {}).get("result_cache"),
            "first_query_ms": first_ms,
            "single_cold_p50_ms": out["single"]["latency_p50_ms"],
            # admission estimates seeded from the gossiped history when
            # the cost model is blind (the flat-default fallback path)
            "est_seeded_fleet": counters.get("est_seeded_fleet", 0),
            "est_seeded_history": counters.get("est_seeded_history", 0),
            "state_gen": counters.get("state_gen", 0),
        }
        stop_gossip.set()
        gt.join(timeout=5)
        out["router_counters"] = {
            k: v for k, v in router3.gauges().get("aggregate", {}).items()}
        out["scale_signal"] = router3.scale_signal()
        for r in reps + [cold]:
            r.shutdown()
        return out
    finally:
        sidecar.stop()
        store.stop()
        shutil.rmtree(d, ignore_errors=True)


def run_fleet_smoke() -> int:
    """``--fleet-smoke``: the CI gate for the serving fleet. Three REAL
    replica subprocesses behind the router take mixed SQL traffic; one
    replica is killed mid-run (traffic must re-route, answers must stay
    right) and one is gracefully drained after (its sessions must be
    released, not orphaned). Exit 1 on a wrong answer, an admission
    leak, an orphaned session queue, zero fleet-tier hits, or any
    lock-order sanitizer cycle inside any replica."""
    import shutil
    import threading

    import daft_tpu as dt
    from daft_tpu import col
    from daft_tpu.fleet.cache_tier import CacheSidecar
    from daft_tpu.fleet.router import FleetRouter, SubprocessReplica

    d, glob = _fleet_make_table("daft_tpu_fleet_smoke_", n=4000)
    sidecar = CacheSidecar(budget_bytes=64 << 20)
    addr = sidecar.start()
    problems = []
    try:
        expected = dt.read_parquet(glob).groupby("g") \
            .agg(col("v").sum().alias("s")).sort("g").to_pydict()
        reps = [SubprocessReplica.spawn(
            f"r{i}", env={"DAFT_TPU_FLEET_SIDECAR": addr})
            for i in range(3)]
        router = FleetRouter(reps)
        duration_s = float(
            os.environ.get("BENCH_FLEET_SMOKE_SECONDS", "8"))
        traffic = {}

        def run_traffic():
            traffic.update(_fleet_traffic(
                router, glob, duration_s, 6, "smoke",
                expected_agg=expected))

        tt = threading.Thread(target=run_traffic, daemon=True)
        tt.start()
        time.sleep(duration_s * 0.4)
        router.gossip_round()
        victim = reps[0].name
        router.kill(victim)   # mid-traffic crash: re-route must absorb
        tt.join(timeout=duration_s + 160)
        router.gossip_round()
        if traffic.get("completed", 0) == 0:
            problems.append("no queries completed")
        # the kill window races in-flight requests: those surface as
        # recorded failures; anything else (wrong answer) is fatal
        fatal = [f for f in traffic.get("failures", [])
                 if "mismatch" in f]
        if fatal:
            problems.append(f"wrong answers: {fatal}")
        if traffic.get("fleet_hits", 0) == 0:
            problems.append("no fleet cache-tier hits across replicas")
        alive = [r for r in reps if r.name != victim]
        # graceful drain: sessions must be RELEASED on the drained
        # replica (no orphaned queues) and re-homed by the router
        drained = alive[0]
        router.drain(drained.name)
        leftover = drained.sessions()
        if leftover:
            problems.append(
                f"orphaned session queues on drained replica: {leftover}")
        for r in alive:
            g = r.gauges()
            if g.get("admitted_bytes", 0):
                problems.append(
                    f"admission leak on {r.name}: {g['admitted_bytes']}")
            c = r.counters()
            if c.get("lock_graph_cycles", 0):
                problems.append(
                    f"lock-order cycles on {r.name}: "
                    f"{c['lock_graph_cycles']}")
            if len([o for o in (r.state_snapshot().get("origins") or {})
                    ]) < 2:
                problems.append(f"gossip never reached {r.name}")
        result = {"fleet_smoke": {
            "completed": traffic.get("completed", 0),
            "qps": traffic.get("qps", 0),
            "fleet_hits": traffic.get("fleet_hits", 0),
            "result_cache_hit_rate":
                traffic.get("result_cache_hit_rate", 0),
            "rerouted_failures_during_kill":
                traffic.get("n_failures", 0),
            "killed": victim, "drained": drained.name,
            "problems": problems[:8],
        }}
        print(json.dumps(result), flush=True)
        for r in reps:
            r.shutdown()
        return 1 if problems else 0
    finally:
        sidecar.stop()
        shutil.rmtree(d, ignore_errors=True)


def run_obs_bench():
    """``--obs``: tracing-overhead measurement on the serve-bench mixed
    workload. Three runs of the same closed-loop traffic: tracing OFF,
    SAMPLED (10%), and FULL — the artifact records QPS and p99 deltas
    vs the off baseline. Gate (documented in README): full tracing must
    cost < 5% QPS."""
    modes = [("off", {"DAFT_TPU_TRACE": "0"}),
             ("sampled", {"DAFT_TPU_TRACE": "1",
                          "DAFT_TPU_TRACE_SAMPLE": "0.1"}),
             ("full", {"DAFT_TPU_TRACE": "1",
                       "DAFT_TPU_TRACE_SAMPLE": "1.0"})]
    duration = float(os.environ.get("BENCH_OBS_SECONDS", "12"))
    # discarded FULL-LENGTH warm-up: the first serve run pays datagen +
    # per-shape jit warm-up (7 query shapes); charging any of that to
    # the "off" baseline would fake a tracing speedup — a 6s warm-up
    # measurably wasn't enough (first committed r13 attempt)
    run_serve_bench(duration_s=duration)
    out = {}
    for name, env in modes:
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            r = run_serve_bench(duration_s=duration)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        out[name] = {"qps": r.get("qps"),
                     "latency_p50_ms": r.get("latency_p50_ms"),
                     "latency_p99_ms": r.get("latency_p99_ms"),
                     "completed": r.get("completed")}
    base_qps = out["off"]["qps"] or 1e-9
    for name in ("sampled", "full"):
        qps = out[name]["qps"] or 0
        out[name]["qps_overhead_pct"] = round(
            100.0 * (base_qps - qps) / base_qps, 2)
        p99b = out["off"]["latency_p99_ms"] or 1e-9
        out[name]["p99_delta_pct"] = round(
            100.0 * ((out[name]["latency_p99_ms"] or 0) - p99b) / p99b, 2)
    out["gate_full_overhead_pct"] = 5.0
    out["gate_pass"] = out["full"]["qps_overhead_pct"] < 5.0
    return out


def run_obs_smoke() -> int:
    """``--obs-smoke``: the observability CI gate. Runs a traced local
    query and a traced distributed query, validates the exported Chrome
    trace against the schema (required fields, monotonic non-negative
    timestamps, matched phases), checks parent-child consistency (no
    orphan spans), scrapes the dashboard's ``/metrics`` with the strict
    text-format parser, and exercises the flight recorder's byte-cap
    rotation. Exit 1 on any failure (daft-lint runs as its own CI
    step)."""
    import tempfile
    import urllib.request

    import daft_tpu as dt
    import daft_tpu.context as dctx
    from daft_tpu import col, dashboard, tracing
    from daft_tpu import observability as obs
    from daft_tpu.runners.distributed_runner import DistributedRunner

    failures = []
    tmp = tempfile.mkdtemp(prefix="daft_tpu_obs_smoke_")
    os.environ["DAFT_TPU_TRACE"] = "1"
    os.environ["DAFT_TPU_TRACE_DIR"] = os.path.join(tmp, "traces")
    os.environ["DAFT_TPU_QUERY_LOG"] = os.path.join(tmp, "queries.jsonl")
    os.environ["DAFT_TPU_QUERY_LOG_BYTES"] = "20000"
    try:
        # 1) traced local query → exported chrome trace validates
        df = (dt.from_pydict({"x": list(range(5000)),
                              "g": [i % 11 for i in range(5000)]})
              .where(col("x") > 10)
              .groupby("g").agg(col("x").sum().alias("s")))
        assert len(df.sort("g").to_pydict()["g"]) == 11
        import glob as g
        files = g.glob(os.path.join(tmp, "traces", "trace_*.json"))
        if not files:
            failures.append("no chrome trace exported for local query")
        else:
            doc = json.load(open(files[0]))
            probs = tracing.validate_chrome_trace(doc)
            if probs:
                failures.append(f"chrome trace invalid: {probs[:3]}")
            names = {e["name"] for e in doc["traceEvents"]
                     if e.get("ph") == "X"}
            for want in ("query", "plan:optimize"):
                if want not in names:
                    failures.append(f"trace missing {want!r} span")

        # 2) traced distributed query → merged trace, no orphans,
        #    worker/fetch spans present
        runner = DistributedRunner(num_workers=2)
        old = dctx.get_context()._runner
        dctx.get_context().set_runner(runner)
        try:
            ddf = (dt.from_pydict({"k": [i % 5 for i in range(4000)],
                                   "v": [float(i) for i in range(4000)]})
                   .into_partitions(3)
                   .groupby("k").agg(col("v").sum().alias("s")))
            assert len(ddf.sort("k").to_pydict()["k"]) == 5
        finally:
            dctx.get_context().set_runner(old)
            if runner._manager is not None:
                runner._manager.shutdown()
        stats = obs.last_query_stats()
        rec = stats.trace_ctx.recorder if stats.trace_ctx else None
        if rec is None:
            failures.append("distributed query produced no trace")
        else:
            orph = tracing.orphan_spans(rec)
            if orph:
                failures.append(f"{len(orph)} orphan spans")
            kinds = {s["name"] for s in rec.spans()}
            for want in ("task", "task:run", "stage"):
                if want not in kinds:
                    failures.append(f"merged trace missing {want!r}")

        # 3) /metrics scrapes and parses strictly
        port = dashboard.launch(0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                text = r.read().decode()
            metrics = tracing.parse_prometheus_text(text)
            if "daft_tpu_flight_recorder_queries_total" not in metrics:
                failures.append("flight recorder metric missing")
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/history",
                    timeout=10) as r:
                hist = json.loads(r.read())
            if not hist:
                failures.append("/api/history empty after traced queries")
        finally:
            dashboard.shutdown()

        # 4) flight recorder rotates at its byte cap
        for i in range(200):
            tracing.flight_record({"ts": "t", "wall_us": i,
                                   "pad": "x" * 256})
        qlog = os.environ["DAFT_TPU_QUERY_LOG"]
        if not os.path.exists(qlog + ".1"):
            failures.append("flight recorder never rotated at byte cap")
        elif os.path.getsize(qlog) > 20000:
            failures.append("flight recorder exceeded its byte cap")

        print(json.dumps({"obs_smoke": {
            "failures": failures[:10], "ok": not failures}}), flush=True)
        return 1 if failures else 0
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
        for k in ("DAFT_TPU_TRACE", "DAFT_TPU_TRACE_DIR",
                  "DAFT_TPU_QUERY_LOG", "DAFT_TPU_QUERY_LOG_BYTES"):
            os.environ.pop(k, None)


def run_kernels_bench():
    """``--kernels``: the hash-vs-sort device kernel sweep (round 12).

    Sweeps the grouped-agg family over rows × NDV × key widths and the
    join family over rows × match shapes, running BOTH strategies on
    every point: parity is asserted (order-insensitive group maps,
    order-EXACT join pair lists), the cost model's per-dispatch pick is
    recorded next to what it would pick on silicon, and on a real chip
    each strategy is re-timed in-jit (``lax.fori_loop``, the r6 harness)
    so the hash-vs-sort ratio is a roofline claim. On a CPU dev box the
    Pallas kernels run under the interpreter — a timing there measures
    the emulator, not silicon — so the artifact reports interpreter-mode
    parity plus the statically re-proven dispatch contracts instead of
    MFU (the acceptance evidence tier-1 can actually produce)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from daft_tpu.analysis import rule_jit
    from daft_tpu.device import backend as dbackend
    from daft_tpu.device import costmodel, kernels as K, mfu
    from daft_tpu.device import pallas_kernels as pk

    interpret = pk.interpret_default()
    out = {
        "interpret": interpret,
        "backend": dbackend.backend_name() or "cpu",
        "agg_sweep": [], "join_sweep": [],
    }

    def silicon_pick(fn, *args, **kw):
        """What the strategy model would decide with a hash-capable
        backend attached (the CPU sweep's 'on silicon this dispatch
        goes hash' column)."""
        real = costmodel._hash_capable_backend
        costmodel._hash_capable_backend = lambda: True
        try:
            return fn(*args, **kw)
        finally:
            costmodel._hash_capable_backend = real

    def agg_map(res, nk, nv):
        ok, okv, ov, ovv, g = res
        g = int(np.asarray(jax.device_get(g)))
        ok = [np.asarray(x) for x in ok]
        okv = [np.asarray(x) for x in okv]
        ov = [np.asarray(x) for x in ov]
        ovv = [np.asarray(x) for x in ovv]
        return {tuple(k[i].item() if kv[i] else None
                      for k, kv in zip(ok, okv)):
                tuple(round(v[i].item(), 3) if vv[i] else None
                      for v, vv in zip(ov, ovv))
                for i in range(g)}

    # ---- grouped-agg: rows × NDV × key widths (1 word / 2 words / wide)
    key_cfgs = (("1xi32", 1), ("2xi64", 2), ("3xi64", 3))
    rows_list = (1 << 12, 1 << 14) if interpret else (1 << 16, 1 << 20)
    parity_all = True
    for C in rows_list:
        for ndv in (16, 256, 2048):
            if ndv * 4 > C:
                continue
            v = np.arange(C) % ndv
            for name, nk in key_cfgs:
                if nk == 1:
                    keys = (jnp.asarray(v.astype(np.int32)),)
                    dts = [np.dtype("int32")]
                else:
                    parts = [(v >> (4 * i)) & 0xF for i in range(nk - 1)]
                    parts.append(v >> (4 * (nk - 1)))
                    keys = tuple(jnp.asarray(p.astype(np.int64))
                                 for p in parts)
                    dts = [np.dtype("int64")] * nk
                ones = jnp.ones(C, bool)
                kvalids = (ones,) * nk
                vals = (jnp.asarray((v % 97).astype(np.float32)),
                        jnp.asarray(np.ones(C, np.float32)))
                vvalids = (ones, ones)
                ops = ("sum", "count")
                out_cap = max(ndv, 128)
                entry = {"rows": C, "ndv": ndv, "keys": name,
                         "hash_fits": pk.hash_pack_words(dts) is not None,
                         "auto_pick": costmodel.groupby_strategy(
                             C, float(ndv), dts, out_cap, log=False)[0],
                         "silicon_pick": silicon_pick(
                             lambda: costmodel.groupby_strategy(
                                 C, float(ndv), dts, out_cap,
                                 log=False)[0])}
                sort_res = K.grouped_agg_block_impl(
                    keys, kvalids, vals, vvalids, ones, ops, out_cap)
                if entry["hash_fits"]:
                    hash_res = pk.hash_grouped_agg_impl(
                        keys, kvalids, vals, vvalids, ones, ops, out_cap)
                    entry["parity"] = (
                        agg_map(hash_res, nk, 2) == agg_map(sort_res,
                                                            nk, 2))
                    entry["load_factor"] = round(
                        ndv / pk.table_capacity(out_cap), 3)
                else:
                    # wide key sets route to the LSD-radix sort path —
                    # the fallback IS the tested behaviour
                    entry["parity"] = entry["silicon_pick"] == "sort"
                parity_all &= entry["parity"]
                out["agg_sweep"].append(entry)

    # ---- join: rows × match shape (fk-shaped vs heavy duplicates)
    join_rows = ((1 << 11, 1 << 9), (1 << 11, 32), (1 << 13, 1 << 11)) \
        if interpret else ((1 << 16, 1 << 14), (1 << 16, 1 << 8))
    for C, ndv in join_rows:
        rng = np.random.default_rng(C + ndv)
        lk = jnp.asarray(rng.integers(0, ndv, C).astype(np.int64))
        rk = jnp.asarray(rng.integers(0, ndv, C).astype(np.int64))
        ones = jnp.ones(C, bool)
        cap = 1 << int(np.ceil(np.log2(4 * C * max(C // ndv, 1))))
        hashed = np.asarray(pk.hash_join_impl(
            lk, ones, ones, rk, ones, ones, cap))
        sorted_ = np.asarray(K.join_fused_impl(
            lk, ones, ones, rk, ones, ones, cap))
        total = int(hashed[2].sum())
        match = total <= cap \
            and np.array_equal(hashed[:2, :total], sorted_[:2, :total]) \
            and np.array_equal(hashed[2], sorted_[2])
        parity_all &= match
        out["join_sweep"].append({
            "rows": C, "build_ndv": ndv, "pairs": total,
            "parity_pair_exact": match,
            "auto_pick": costmodel._join_strategy(C, C),
            "silicon_pick": silicon_pick(
                lambda: costmodel._join_strategy(C, C))})
    out["parity_all"] = parity_all

    # ---- dispatch contracts, re-proven from freshly built jaxprs (the
    # same single-sourced checker `python -m daft_tpu.analysis` runs)
    findings = rule_jit.check_dispatch_contracts()
    out["dispatch_contracts"] = {
        "clean": not findings,
        "findings": [str(f)[:160] for f in findings][:5],
        "hash_agg_pallas_calls": rule_jit.HASH_AGG_PALLAS_CALLS,
        "hash_join_pallas_calls": rule_jit.HASH_JOIN_PALLAS_CALLS,
        "hash_join_sort_free": True,
    }

    # ---- roofline: silicon-only (the interpreter would time the
    # emulator); the r05 baseline rows are the ledger numbers this round
    # exists to beat — grouped-agg 0.067% of the HBM roofline, join
    # 0.004% MFU (BENCH_r05 `mfu` block)
    if not interpret:
        rep = mfu.report(n=1 << 20)
        out["mfu"] = rep
        agg_h = rep.get("grouped_agg_hash", {}).get("roofline_pct")
        agg_s = rep.get("grouped_agg", {}).get("roofline_pct")
        join_h = rep.get("join_hash", {}).get("roofline_pct")
        join_s = rep.get("join", {}).get("roofline_pct")
        if agg_h and agg_s:
            out["agg_improvement_vs_sort"] = round(agg_h / agg_s, 2)
        if join_h and join_s:
            out["join_improvement_vs_sort"] = round(join_h / join_s, 2)
        out["r05_baseline"] = {"grouped_agg_roofline_pct": 0.067,
                               "join_mfu_pct": 0.004}
        if agg_h:
            out["agg_improvement_vs_r05"] = round(agg_h / 0.067, 1)
    else:
        out["mfu"] = {
            "skipped": "interpreter backend — parity + dispatch "
                       "contracts are the CPU evidence; roofline claims "
                       "come from silicon runs (see the device child's "
                       "mfu block)"}
    return out


def run_arrow_baseline():
    import pyarrow.compute as pc
    import pyarrow.dataset as pads
    t0 = time.time()
    t = pads.dataset(os.path.join(DATA, "lineitem")).to_table()
    t = t.filter(pc.field("l_shipdate") <= datetime.date(1998, 9, 2))
    disc = pc.multiply(t.column("l_extendedprice"),
                       pc.subtract(1.0, t.column("l_discount")))
    charge = pc.multiply(disc, pc.add(1.0, t.column("l_tax")))
    t = t.append_column("disc_price", disc).append_column("charge", charge)
    g = t.group_by(["l_returnflag", "l_linestatus"]).aggregate(
        [("l_quantity", "sum"), ("l_extendedprice", "sum"),
         ("disc_price", "sum"), ("charge", "sum"), ("l_quantity", "mean"),
         ("l_extendedprice", "mean"), ("l_discount", "mean"),
         ("l_quantity", "count")])
    g = g.sort_by([("l_returnflag", "ascending"),
                   ("l_linestatus", "ascending")])
    return g, time.time() - t0


def pinned_arrow_baseline():
    """Best-of-3 Arrow Q1 baseline, persisted once per dataset. The r2→r3
    headline `vs_baseline` swung 105×→13× purely on denominator contention;
    pinning makes consecutive runs agree. Delete the cache file to re-pin.

    Returns (num_q1_groups, seconds)."""
    cache = os.path.join(DATA, "arrow_baseline_q1.json")
    if os.path.exists(cache):
        with open(cache) as f:
            d = json.load(f)
        return d["q1_groups"], d["seconds"]
    best, groups = None, None
    for _ in range(3):
        tbl, s = run_arrow_baseline()
        groups = tbl.num_rows
        best = s if best is None else min(best, s)
    with open(cache, "w") as f:
        json.dump({"q1_groups": groups, "seconds": round(best, 3),
                   "method": "best-of-3, uncontended"}, f)
    return groups, best


# ----------------------------------------------------------- device child

def _emit(obj):
    print(json.dumps(obj), flush=True)


def _device_child():
    """Child-process entry with the device tier on. One JSON line per
    section, cheapest/most-important first, so a stall or timeout only
    loses the sections after it."""
    os.environ["DAFT_TPU_DEVICE"] = "1"
    budget = float(os.environ.get("BENCH_DEVICE_BUDGET_S", DEVICE_TIMEOUT))
    deadline = time.time() + budget * 0.92

    out, warm, hot = run_tpch_query(DATA, "q1")
    from daft_tpu.device import backend as dbackend
    # emit the headline BEFORE the extra spread samples: a timeout during
    # them must only lose the spread, never the Q1 section itself
    _emit({"warm": warm, "hot": hot,
           "groups": len(next(iter(out.values()))),
           "backend": dbackend.backend_name() or "host-fallback"})
    _, w3, h3 = run_tpch_query(DATA, "q1")  # 3 hot samples → median + spread
    _emit({"runs": sorted(round(x, 3) for x in (hot, w3, h3))})

    # single-chip kernel efficiency: MFU for the MXU grouped agg, HBM
    # roofline % for the memory-bound families (BASELINE's efficiency
    # currency). Round 6: repetition runs INSIDE one jit program
    # (lax.fori_loop) so the number measures silicon, not tunnel RTT —
    # the r5 artifact's 0.23%/0.004% figures were mostly wire time. The
    # embedded `ledger` carries the per-dispatch accounting of the REAL
    # Q1 dispatches that already ran above.
    if time.time() < deadline:
        from daft_tpu.device import mfu
        # 1M rows saturates a real chip; a CPU backend (virtual-mesh dev
        # box) takes minutes at that size and would eat the child budget
        # before the suites — scale down, the numbers are only meaningful
        # on silicon anyway
        n_mfu = 1 << 20 if (dbackend.backend_name() or "cpu") != "cpu" \
            else 1 << 16
        _emit({"mfu": mfu.report(n=n_mfu)})

    for qn in ("q6", "q3", "q10"):
        if time.time() > deadline:
            return
        _, w, h = run_tpch_query(DATA, qn)
        _emit({f"{qn}_warm": round(w, 3), f"{qn}_hot": round(h, 3)})

    if time.time() < deadline:
        suite = run_tpch_suite(DATA, budget_s=deadline - time.time())
        _emit({"tpch_sf1_suite": suite})

    if time.time() < deadline:
        try:
            _emit({"tpcds": run_tpcds_trio(TPCDS_DATA)})
        except Exception as exc:
            _emit({"tpcds": {"error": str(exc)[:200]}})

    if time.time() < deadline:
        try:
            _emit({"laion": run_laion(LAION_DATA)})
        except Exception as exc:
            _emit({"laion": {"error": str(exc)[:200]}})

    if os.path.isdir(os.path.join(SF10_DATA, "lineitem")) \
            and time.time() < deadline:
        sf10 = run_tpch_suite(SF10_DATA, budget_s=deadline - time.time())
        _emit({"tpch_sf10_suite": sf10})

    # whole-suite per-dispatch ledger LAST: every device dispatch of every
    # section above is accounted (the committed artifact's evidence that
    # the efficiency numbers describe real engine work, not just the
    # synthetic harness)
    from daft_tpu.device import costmodel
    _emit({"mfu_ledger": costmodel.ledger_snapshot()})


def _try_device_tier(budget_s: float):
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--device-child"],
            capture_output=True, text=True, timeout=budget_s,
            cwd=REPO, env={**os.environ, "DAFT_TPU_DEVICE": "1",
                           "BENCH_DEVICE_BUDGET_S": str(budget_s)})
    except subprocess.TimeoutExpired as exc:
        print("device tier: timed out; using partial output",
              file=sys.stderr)
        partial = exc.stdout or b""
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        return _merge_lines(partial)
    if proc.returncode != 0:
        print(f"device tier: child failed rc={proc.returncode}\n"
              f"{proc.stderr[-2000:]}", file=sys.stderr)
        return _merge_lines(proc.stdout or "")
    return _merge_lines(proc.stdout or "")


def _warmup_child():
    """``--warmup-child``: one cold process measuring the recompile tax.
    Runs q1/q6/q3 twice each under the armed retrace sanitizer and
    reports first/hot latency plus per-run trace/compile counters (the
    shape-discipline evidence: hot runs must show ZERO trace events).
    With BENCH_WARMUP_AOT=1 it runs the AOT warm-up first, so a
    populated DAFT_TPU_COMPILE_CACHE_DIR turns compiles into disk
    reads."""
    os.environ.setdefault("DAFT_TPU_DEVICE", "1")
    from daft_tpu.analysis import retrace_sanitizer as rs
    if not rs.is_enabled():
        rs.enable(1)
    out = {}
    if os.environ.get("BENCH_WARMUP_AOT") == "1":
        from daft_tpu.device import warmup
        t0 = time.time()
        st = warmup.warmup_session()
        out["aot"] = {"seconds": round(time.time() - t0, 3),
                      "size_classes": st.get("size_classes"),
                      "kernels": st.get("kernels"),
                      "fragments": st.get("fragments")}
    for qn in ("q1", "q6", "q3"):
        s0 = rs.counters_snapshot()
        _out, first, hot = run_tpch_query(DATA, qn)
        s2 = rs.counters_snapshot()
        # run_tpch_query runs warm+hot internally; re-split the counters
        # with one more hot run so the HOT figures are isolated
        s_hot0 = rs.counters_snapshot()
        t0 = time.time()
        run_tpch_query_once(DATA, qn)
        hot2 = time.time() - t0
        s_hot1 = rs.counters_snapshot()
        out[qn] = {
            "first_s": round(first, 3), "hot_s": round(min(hot, hot2), 3),
            "first_traces": int(s2.get("traces", 0) - s0.get("traces", 0)),
            "first_compiles": int(s2.get("compiles", 0)
                                  - s0.get("compiles", 0)),
            "first_compile_s": round(s2.get("compile_seconds", 0)
                                     - s0.get("compile_seconds", 0), 3),
            "hot_traces": int(s_hot1.get("traces", 0)
                              - s_hot0.get("traces", 0)),
            "hot_compiles": int(s_hot1.get("compiles", 0)
                                - s_hot0.get("compiles", 0)),
        }
    s = rs.summary()
    out["retrace_violations"] = s.get("violations", [])
    print(json.dumps(out))


def run_tpch_query_once(root, qname: str):
    from benchmarking.tpch import queries as Q
    get_df = _get_df_factory(root)
    return getattr(Q, qname)(get_df).to_pydict()


def run_warmup_bench():
    """``--warmup``: cold-process → first-query latency and hot repeat,
    with and without AOT warm-up + a persisted XLA compilation cache,
    plus per-query retrace counts (ROADMAP item 1's <5s warm-up gate).
    Three children: cold baseline; cache-populating AOT run; warm-start
    run re-reading the persisted cache."""
    import shutil
    import tempfile

    def child(extra, budget=420.0):
        # NOTE: no DAFT_TPU_SANITIZE here — the lock sanitizer's proxy
        # overhead would skew the latency numbers; _warmup_child arms
        # the retrace listener directly, which is passive off the
        # trace path
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--warmup-child"],
            capture_output=True, text=True, timeout=budget, cwd=REPO,
            env={**os.environ, "DAFT_TPU_DEVICE": "1", **extra})
        merged = _merge_lines(proc.stdout or "")
        if merged is None:
            raise RuntimeError(
                f"warmup child rc={proc.returncode}: "
                f"{(proc.stderr or '')[-500:]}")
        return merged

    cold = child({})
    cache_dir = tempfile.mkdtemp(prefix="daft_tpu_aot_cache_")
    try:
        aot_env = {"DAFT_TPU_COMPILE_CACHE_DIR": cache_dir,
                   "DAFT_TPU_AOT_WARMUP": "1", "BENCH_WARMUP_AOT": "1"}
        populate = child(aot_env)
        persisted = child(aot_env)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    out = {"cold": cold, "aot_populate": populate,
           "aot_persisted": persisted}
    # the violations gate FIRST and unconditionally: a missing derived
    # metric below must never silently drop real violations from the
    # committed artifact
    out["violations"] = [
        v for child in (cold, populate, persisted)
        for v in child.get("retrace_violations", [])]
    try:
        cold_first = cold["q1"]["first_s"]
        warm_first = persisted["q1"]["first_s"]
        out["q1_cold_first_s"] = cold_first
        out["q1_aot_persisted_first_s"] = warm_first
        out["q1_first_query_speedup"] = round(cold_first / warm_first, 3) \
            if warm_first else None
        out["hot_zero_retraces"] = all(
            child[q]["hot_traces"] == 0
            for child in (cold, populate, persisted)
            for q in ("q1", "q6", "q3"))
        out["compile_s_cold_vs_persisted"] = [
            cold["q1"]["first_compile_s"],
            persisted["q1"]["first_compile_s"]]
    except (KeyError, TypeError):
        pass
    return out


def _device_pipeline_child():
    """``--device-pipeline-child``: one process running device-forced
    q1/q6 at a given ``DAFT_TPU_DEVICE_INFLIGHT``, optionally with a
    simulated transfer-bound link: ``BENCH_PIPE_LINK_MS`` sleeps at the
    engine's real upload/download chokepoints (``column.encode_batch``,
    ``pipeline.fetch_host``) — the scan bench's latency-injected object
    store, applied to the device link, so a CPU dev box exercises the
    overlap a tunneled chip would see.  Reports hot walls, answers
    (parity evidence), the pipeline overlap ledger row, and residency
    counters."""
    os.environ["DAFT_TPU_DEVICE"] = "1"
    os.environ.setdefault("DAFT_TPU_DEVICE_FORCE", "1")
    delay_ms = float(os.environ.get("BENCH_PIPE_LINK_MS", "0"))
    link_mbps = float(os.environ.get("BENCH_PIPE_LINK_MBPS", "40"))
    if delay_ms > 0:
        import jax

        import daft_tpu.device.column as dcol
        import daft_tpu.device.pipeline as dpipe
        real_fetch, real_encode = dpipe.fetch_host, dcol.encode_batch

        def _link_sleep(nbytes):
            # one RTT per transfer + wire time at the simulated
            # bandwidth — the r9 scan bench's latency-injected object
            # store, applied to the device link
            time.sleep(delay_ms / 1e3 + nbytes / (link_mbps * 1e6))

        def slow_fetch(tree):
            # charge the link only for REAL device transfers — numpy
            # passthroughs (already-fetched planes re-entering decode)
            # cost nothing on a real wire either
            dev = [x for x in jax.tree_util.tree_leaves(tree)
                   if isinstance(x, jax.Array)]
            if dev:
                _link_sleep(sum(int(x.nbytes) for x in dev))
            return real_fetch(tree)

        def slow_encode(batch, columns=None):
            dt = real_encode(batch, columns)
            # residency-reuse hits perform no upload — a real wire
            # carries nothing for them (symmetric with slow_fetch's
            # numpy-passthrough filter)
            if not dt.resident:
                _link_sleep(sum(
                    int(c.data.nbytes) + int(c.validity.nbytes)
                    for c in dt.columns.values()))
            return dt

        dpipe.fetch_host = slow_fetch
        dcol.encode_batch = slow_encode
    if os.environ.get("DAFT_TPU_AOT_WARMUP") == "1":
        from daft_tpu.device import warmup
        warmup.warmup_session()
    from daft_tpu.device import costmodel, pipeline as dpipe2
    out = {"window": int(os.environ.get("DAFT_TPU_DEVICE_INFLIGHT", "2")),
           "link_delay_ms": delay_ms}
    for qn in ("q1", "q6"):
        res, warm, hot = run_tpch_query(DATA, qn)
        out[qn] = {"warm_s": round(warm, 3), "hot_s": round(hot, 3),
                   "answer": {k: v[:8] for k, v in res.items()}}
    snap = costmodel.ledger_snapshot()
    out["pipeline_ledger"] = snap.get("pipeline", {})
    # per-dispatch-family evidence (grouped_agg / projection / argsort
    # rows with seconds + overlap fields where the pipeline drove them)
    out["mfu_ledger"] = snap
    out["residency"] = dpipe2.residency_counters()
    print(json.dumps(out))


def run_device_pipeline_bench():
    """``--device-pipeline``: pipelined vs synchronous device execution.
    Five cold children — windows {0 (synchronous), 2, BENCH_PIPE_WINDOW
    (default 4)} on the simulated slow link plus a bare {0, deep} pair —
    measure q1/q6 hot walls, verify bit-identical answers, and report
    the overlap ratio (serial-equivalent stage seconds vs pipelined
    active wall) plus the transfer seconds the window hid.  The
    headline gate: pipelined device q1 hot ≤ 0.6× the synchronous
    path on the transfer-bound configuration."""
    def child(window, delay_ms):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--device-pipeline-child"],
            capture_output=True, text=True, timeout=420, cwd=REPO,
            env={**os.environ, "DAFT_TPU_DEVICE": "1",
                 "DAFT_TPU_DEVICE_FORCE": "1",
                 "DAFT_TPU_DEVICE_INFLIGHT": str(window),
                 # r16 AOT warm-up rides along so the walls measure the
                 # pipeline, not first-trace compiles
                 "DAFT_TPU_AOT_WARMUP": "1",
                 # finer scan tasks → enough windows for the in-flight
                 # ladder to actually overlap on SF1
                 "DAFT_SCAN_TASKS_MIN_SIZE_BYTES": str(8 << 20),
                 "BENCH_PIPE_LINK_MS": str(delay_ms)})
        merged = _merge_lines(proc.stdout or "")
        if merged is None:
            raise RuntimeError(
                f"device-pipeline child rc={proc.returncode}: "
                f"{(proc.stderr or '')[-500:]}")
        return merged

    delay = float(os.environ.get("BENCH_PIPE_LINK_MS", "50"))
    deep = int(os.environ.get("BENCH_PIPE_WINDOW", "4"))
    sync = child(0, delay)
    piped2 = child(2, delay)
    piped_deep = child(deep, delay)
    bare_sync = child(0, 0)
    bare_piped = child(deep, 0)
    out = {"link_delay_ms": delay, "sync": sync,
           "pipelined_w2": piped2, f"pipelined_w{deep}": piped_deep,
           "bare_sync_hot_s": {qn: bare_sync[qn]["hot_s"]
                               for qn in ("q1", "q6")},
           "bare_pipelined_hot_s": {qn: bare_piped[qn]["hot_s"]
                                    for qn in ("q1", "q6")}}
    out["parity_all"] = all(
        piped2[qn]["answer"] == sync[qn]["answer"]
        and piped_deep[qn]["answer"] == sync[qn]["answer"]
        and bare_piped[qn]["answer"] == bare_sync[qn]["answer"]
        for qn in ("q1", "q6"))
    best = piped_deep if piped_deep["q1"]["hot_s"] <= piped2["q1"]["hot_s"] \
        else piped2
    out["best_window"] = best["window"]
    for qn in ("q1", "q6"):
        s, p = sync[qn]["hot_s"], best[qn]["hot_s"]
        out[f"{qn}_hot_ratio"] = round(p / s, 3) if s else None
        out[f"{qn}_hot_ratio_w2"] = round(
            piped2[qn]["hot_s"] / s, 3) if s else None
    led = best.get("pipeline_ledger") or {}
    if led.get("serial_equiv_s") and led.get("seconds"):
        out["overlap_x"] = led.get("overlap_x")
        out["transfer_s_hidden"] = round(
            led["serial_equiv_s"] - led["seconds"], 3)
    out["gate_q1_ratio_le_0.6"] = bool(
        out.get("q1_hot_ratio") is not None
        and out["q1_hot_ratio"] <= 0.6)
    return out


def _fusion_link_micro():
    """In-process micro: filter→project→top-k over an in-memory source,
    device-forced, per-operator vs fused-region, with the r17 simulated
    transfer-bound link charging every upload/download.  Per-operator
    must ship the FULL projected planes back for the host top-k; the
    fused region sorts in-program and transfers only the k-bucket — the
    download the region eliminates becomes measurable wall time on a
    CPU box the same way it would on a tunneled chip."""
    import jax
    import numpy as np

    import daft_tpu as dt
    import daft_tpu.device.column as dcol
    import daft_tpu.device.pipeline as dpipe
    from daft_tpu import col
    delay_ms = float(os.environ.get("BENCH_FUSION_LINK_MS", "2"))
    link_mbps = float(os.environ.get("BENCH_FUSION_LINK_MBPS", "40"))
    real_fetch, real_encode = dpipe.fetch_host, dcol.encode_batch
    xfer = {}

    def _link_sleep(nbytes):
        time.sleep(delay_ms / 1e3 + nbytes / (link_mbps * 1e6))

    def slow_fetch(tree):
        dev = [x for x in jax.tree_util.tree_leaves(tree)
               if isinstance(x, jax.Array)]
        if dev:
            nb = sum(int(x.nbytes) for x in dev)
            xfer["down_bytes"] = xfer.get("down_bytes", 0) + nb
            xfer["downloads"] = xfer.get("downloads", 0) + 1
            _link_sleep(nb)
        return real_fetch(tree)

    def slow_encode(batch, columns=None):
        t = real_encode(batch, columns)
        if not t.resident:   # residency hits carry nothing on a real wire
            nb = sum(int(c.data.nbytes) + int(c.validity.nbytes)
                     for c in t.columns.values())
            xfer["up_bytes"] = xfer.get("up_bytes", 0) + nb
            xfer["uploads"] = xfer.get("uploads", 0) + 1
            _link_sleep(nb)
        return t

    rng = np.random.default_rng(21)
    n = 1 << 21
    data = {"a": rng.integers(0, 100, n).astype(np.int64),
            "b": rng.normal(size=n), "c": rng.normal(size=n)}

    def q():
        df = dt.from_pydict(data)
        return (df.where(col("a") < 95)
                .select((col("b") * 2.0 + col("c")).alias("x"), col("a"))
                .sort(col("x"), desc=True).limit(32)
                .to_pydict())

    saved = {k: os.environ.get(k)
             for k in ("DAFT_TPU_FUSION", "DAFT_TPU_DEVICE_FORCE")}
    os.environ["DAFT_TPU_DEVICE_FORCE"] = "1"
    dpipe.fetch_host, dcol.encode_batch = slow_fetch, slow_encode
    res = {}
    try:
        for mode in ("0", "1"):
            os.environ["DAFT_TPU_FUSION"] = mode
            q()   # warm: traces + compiles off the measured run
            xfer.clear()
            t0 = time.time()
            out = q()
            res[mode] = {"hot_s": round(time.time() - t0, 3),
                         "rows": len(out["x"]),
                         "answer": _canon_rows(out), **xfer}
    finally:
        dpipe.fetch_host, dcol.encode_batch = real_fetch, real_encode
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    parity = res["0"]["answer"] == res["1"]["answer"]
    for m in res.values():
        m.pop("answer")
    fused, per_op = res["1"]["hot_s"], res["0"]["hot_s"]
    return {"rows": n, "link_delay_ms": delay_ms, "link_mbps": link_mbps,
            "per_operator": res["0"], "fused": res["1"],
            "fused_over_per_op": round(fused / per_op, 3) if per_op
            else None, "parity": parity}


def _fusion_child():
    """``--fusion-child``: one process, one fusion configuration (the
    driver sets DAFT_TPU_DEVICE / DAFT_TPU_FUSION / DAFT_TPU_CALIBRATION
    in the env).  Emits q1/q3/q6 walls + canonical answers (cross-config
    parity evidence), the SF1 suite wall, the ``region`` ledger family,
    and — with BENCH_FUSION_MICRO=1 — the simulated-link chain micro."""
    budget = float(os.environ.get("BENCH_FUSION_BUDGET_S", "360"))
    deadline = time.time() + budget * 0.92

    def safe_rows(rows):
        # date cells aren't JSON; stringified they still compare equal
        # across children
        return [[v if isinstance(v, (str, int, float, bool, type(None)))
                 else str(v) for v in r] for r in rows]

    for qn in ("q1", "q3", "q6"):
        out, warm, hot = run_tpch_query(DATA, qn)
        _emit({qn: {"warm_s": round(warm, 3), "hot_s": round(hot, 3),
                    "answer": safe_rows(_canon_rows(out))}})
    if os.environ.get("BENCH_FUSION_MICRO") == "1" \
            and time.time() < deadline:
        try:
            _emit({"link_micro": _fusion_link_micro()})
        except Exception as exc:
            _emit({"link_micro": {"error": str(exc)[:200]}})
    if time.time() < deadline:
        _emit({"tpch_sf1_suite": run_tpch_suite(
            DATA, budget_s=deadline - time.time())})
    from daft_tpu.device import costmodel, fragment
    snap = costmodel.ledger_snapshot()
    _emit({"region_ledger": snap.get("region", {}),
           "region_programs": len(fragment.fused_region_programs())})


def _rows_close(a, b, rtol=1e-6, atol=1e-6):
    """Order-insensitive row-set comparison with float tolerance: the
    fused region and the host tier sum in different orders, so revenue
    columns agree to ~1e-9 relative, not bitwise."""
    if a is None or b is None or len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) or isinstance(vb, float):
                if va is None or vb is None:
                    if va is not vb:
                        return False
                elif abs(va - vb) > atol + rtol * abs(vb):
                    return False
            elif va != vb:
                return False
    return True


def run_fusion_bench():
    """``--fusion``: whole-query device compilation (round 21).  Three
    cold children over identical data — host, device per-fragment
    (DAFT_TPU_FUSION=0), device fused (DAFT_TPU_FUSION=auto) — report
    q1/q3/q6 hot walls + the SF1 suite wall; answers must agree across
    all three (``parity_all``).  Both device children run with the
    runtime-calibrated cost model (round 20) — the honest device tier,
    with observed rates routing device-losing fragments host.  The
    fused child also runs the simulated-link chain micro: per-operator
    vs one-program dispatch with every round-trip charged wire time."""
    budget = float(os.environ.get("BENCH_FUSION_BUDGET_S", "360"))

    def child(env):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--fusion-child"],
            capture_output=True, text=True, timeout=budget + 60, cwd=REPO,
            env={**os.environ, "BENCH_FUSION_BUDGET_S": str(budget),
                 **env})
        merged = _merge_lines(proc.stdout or "")
        if merged is None:
            raise RuntimeError(f"fusion child rc={proc.returncode}: "
                               f"{(proc.stderr or '')[-500:]}")
        return merged

    host = child({"DAFT_TPU_DEVICE": "0", "DAFT_TPU_FUSION": "0"})
    frag = child({"DAFT_TPU_DEVICE": "1", "DAFT_TPU_FUSION": "0",
                  "DAFT_TPU_CALIBRATION": "1"})
    fused = child({"DAFT_TPU_DEVICE": "1", "DAFT_TPU_FUSION": "auto",
                   "DAFT_TPU_CALIBRATION": "1", "BENCH_FUSION_MICRO": "1"})

    out = {"budget_s": budget}
    parity_all = True
    for qn in ("q1", "q3", "q6"):
        h, f, u = host.get(qn), frag.get(qn), fused.get(qn)
        if not (h and f and u):
            parity_all = False
            continue
        parity = _rows_close(f["answer"], h["answer"]) \
            and _rows_close(u["answer"], h["answer"])
        parity_all &= parity
        out[qn] = {"host_hot_s": h["hot_s"],
                   "device_per_fragment_hot_s": f["hot_s"],
                   "device_fused_hot_s": u["hot_s"],
                   "parity": parity}
    micro = fused.get("link_micro")
    if micro is not None:
        out["link_micro"] = micro
        if "parity" in micro:
            parity_all &= bool(micro["parity"])
    for name, c in (("host", host), ("device_per_fragment", frag),
                    ("device_fused", fused)):
        s = c.get("tpch_sf1_suite")
        if s is not None:
            out[f"sf1_suite_{name}"] = s
    out["region_ledger"] = fused.get("region_ledger", {})
    out["region_programs"] = fused.get("region_programs", 0)
    out["parity_all"] = parity_all
    return out


def _merge_lines(text: str):
    merged = {}
    for line in text.strip().splitlines():
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict):
            merged.update(parsed)
    return merged or None


# ------------------------------------------------------------------ main

def main():
    skipped: list = []
    errors: dict = {}

    def section(name, fn, min_needed=5.0):
        """Run `fn` only if the global budget affords it; name it in
        `skipped_sections` otherwise; any exception lands LOUDLY in the
        top-level `section_errors`, never silently inside a detail dict."""
        if _remaining() < min_needed:
            skipped.append(name)
            return None
        try:
            return fn()
        except Exception as exc:
            errors[name] = str(exc)[:200]
            return None

    ensure_data()
    import glob as g

    import pyarrow.parquet as pq
    nrows = sum(pq.ParquetFile(p).metadata.num_rows
                for p in g.glob(f"{DATA}/lineitem/*.parquet"))

    base_groups, base_s = pinned_arrow_baseline()

    # host tier first: hang-free, guarantees a number is always reported.
    # Three runs (not two): the r4 postmortem showed the device-vs-host Q1
    # margin flipping sign inside run-to-run noise, so both tiers report
    # median-of-3 plus the spread, and a "win" is only claimed when the
    # margin exceeds the combined spread.
    os.environ["DAFT_TPU_DEVICE"] = "0"
    out, host_warm, host_hot = run_tpch_query(DATA, "q1")
    assert len(out["l_returnflag"]) == base_groups, \
        (len(out["l_returnflag"]), base_groups)
    _, h3w, h3h = run_tpch_query(DATA, "q1")
    host_runs = sorted([host_hot, h3w, h3h])

    host_med = host_runs[1]
    host_spread = host_runs[-1] - host_runs[0]
    detail = {
        "host_warm_s": round(host_warm, 3), "host_hot_s": round(host_hot, 3),
        "host_q1_runs_s": [round(x, 3) for x in host_runs],
        "host_q1_median_s": round(host_med, 3),
        "host_q1_spread_s": round(host_spread, 3),
        "arrow_cpu_baseline_s": round(base_s, 3), "lineitem_rows": nrows,
        "backend": "host",
        "total_budget_s": TOTAL_BUDGET,
    }
    for qn in ("q6", "q3", "q10"):
        r = section(f"{qn}_host", lambda qn=qn: run_tpch_query(DATA, qn))
        if r is not None:
            detail[f"{qn}_host_hot_s"] = round(min(r[1], r[2]), 3)

    ours = min(host_warm, host_hot)

    # device tier next (it carries the headline's best case and its own
    # per-section emission tolerates truncation); it gets at most half the
    # remaining budget so the host suites below always run too
    dev_budget = min(DEVICE_TIMEOUT, max(_remaining() * 0.5, 60.0))
    dev = (section("device_tier", lambda: _try_device_tier(dev_budget),
                   min_needed=60.0))
    if dev is not None and dev.get("backend") == "host-fallback":
        detail["device_backend"] = "host-fallback"
        dev = None
    if dev is not None:
        # independent sections are recorded regardless of the Q1 sanity
        # gate below — a Q1 regression must not silently hide them
        for k in ("q6_hot", "q3_hot", "q10_hot"):
            if k in dev:
                detail[f"{k.split('_')[0]}_device_hot_s"] = dev[k]
        for k in ("tpch_sf1_suite", "tpcds", "laion", "tpch_sf10_suite",
                  "mfu", "mfu_ledger"):
            if k in dev:
                detail[f"{k}_device"] = dev[k]
        if dev.get("groups") == base_groups:
            detail["device_warm_s"] = round(dev["warm"], 3)
            detail["device_hot_s"] = round(dev["hot"], 3)
            detail["device_backend"] = dev.get("backend")
            dev_runs = sorted(dev.get("runs") or [dev["hot"]])
            dev_med = dev_runs[len(dev_runs) // 2]
            dev_spread = dev_runs[-1] - dev_runs[0]
            detail["device_q1_runs_s"] = dev_runs
            detail["device_q1_median_s"] = round(dev_med, 3)
            detail["device_q1_spread_s"] = round(dev_spread, 3)
            # variance-aware verdict: a tier only "wins" Q1 when the median
            # margin exceeds the combined observed spread (r4: the claim
            # flipped sign between two same-box runs inside ±5%)
            margin = host_med - dev_med
            noise = host_spread + dev_spread
            detail["q1_winner"] = ("device" if margin > noise
                                   else "host" if -margin > noise else "tie")
            if dev["hot"] < ours:
                ours = dev["hot"]
                detail["backend"] = dev.get("backend", "device")
        elif "groups" in dev:
            detail["device_q1_mismatch"] = \
                {"groups": dev["groups"], "expected": base_groups}

    if "--chaos" in sys.argv:
        # seeded chaos run: recovery-event counts land in the artifact
        # (~55 s observed: Q3 distributed with ~30 map recomputations)
        r = section("chaos", lambda: run_chaos(DATA), min_needed=70.0)
        if r is not None:
            detail["chaos"] = r

    if "--shuffle" in sys.argv:
        # shuffle data-plane microbench: hash-exchange rows/s, wire bytes,
        # compression ratio, combine reduction, fetch overlap
        r = section("shuffle", run_shuffle_bench, min_needed=40.0)
        if r is not None:
            detail["shuffle_bench"] = r
        # pod-native exchange ladder: flight vs collective vs hierarchical
        # on the simulated 8-device pod (cold children), rows/s +
        # bytes-per-link + stream counts + parity
        r = section("mesh_exchange", run_mesh_exchange_bench,
                    min_needed=60.0)
        if r is not None:
            detail["mesh_exchange_bench"] = r

    if "--spill" in sys.argv or "--scale" in sys.argv:
        # out-of-core execution: forced-tiny-budget grace join + spilled
        # agg parity vs in-memory, spill bytes + recursion evidence, and
        # the r23 fast-path A/B (legacy serial+none vs pooled+lz4)
        r = section("spill", run_spill_bench, min_needed=40.0)
        if r is not None:
            detail["spill_bench"] = r

    if "--adaptive" in sys.argv:
        # self-tuning feedback loops: runtime re-plan vs static wall on
        # near-unique keys (identical results), calibrated NDV ratio
        # flipping a footer-mispredicted combine decision
        r = section("adaptive", run_adaptive_bench, min_needed=60.0)
        if r is not None:
            detail["adaptive_bench"] = r

    if "--scan" in sys.argv:
        # scan-side IO plane microbench: GET coalescing + parallel fetch +
        # prefetch pipelining against a latency-injected local object store
        r = section("scan", run_scan_bench, min_needed=40.0)
        if r is not None:
            detail["scan_bench"] = r

    if "--device-pipeline" in sys.argv:
        # async device pipeline: pipelined vs synchronous q1/q6 device
        # walls (simulated transfer-bound link), parity, overlap ratio
        r = section("device_pipeline", run_device_pipeline_bench,
                    min_needed=60.0)
        if r is not None:
            detail["device_pipeline_bench"] = r

    if "--fusion" in sys.argv:
        # whole-query compilation: host vs per-fragment vs fused-region
        # q1/q3/q6 + SF1 suite walls, link-charged chain micro, parity
        r = section("fusion", run_fusion_bench, min_needed=120.0)
        if r is not None:
            detail["fusion_bench"] = r

    if "--warmup" in sys.argv:
        # shape-discipline bench: cold vs AOT+persisted-cache first-query
        # latency + per-query retrace counts (hot repeats must be zero)
        r = section("warmup", run_warmup_bench, min_needed=60.0)
        if r is not None:
            detail["warmup_bench"] = r

    if "--kernels" in sys.argv:
        # hash-vs-sort kernel sweep: parity over NDV × rows × key widths,
        # dispatch-contract re-proof, roofline ratios on silicon
        r = section("kernels", run_kernels_bench, min_needed=40.0)
        if r is not None:
            detail["kernels_bench"] = r

    if "--obs" in sys.argv:
        # tracing-overhead measurement: off vs sampled vs full tracing on
        # the serve-bench mixed workload (QPS/p99 deltas, <5% full gate)
        r = section("obs", run_obs_bench, min_needed=120.0)
        if r is not None:
            detail["obs_bench"] = r

    if "--serve" in sys.argv:
        # serving plane: sustained mixed traffic through the query
        # scheduler — QPS, p50/p99 latency, queue wait, rejections,
        # plan/result cache hit rates, repeated-vs-cold latency ratio
        # min_needed covers one-time SF0.1 datagen on a fresh checkout
        r = section("serve", run_serve_bench, min_needed=120.0)
        if r is not None:
            detail["serve_bench"] = r

    if "--fleet" in sys.argv:
        # serving fleet: 1 vs 3 subprocess driver replicas under the same
        # closed-loop SQL traffic — aggregate-QPS scaling, shared cache-
        # tier hit rate, cold-replica warm-start from gossiped state
        r = section("fleet", run_fleet_bench, min_needed=90.0)
        if r is not None:
            detail["fleet_bench"] = r

    # --scale: the suite-trajectory mode — per-query spill/governor/RSS/
    # replan/strategy counters ride along in the artifact
    rich = "--scale" in sys.argv
    r = section("tpch_sf1_suite_host",
                lambda: run_tpch_suite(DATA, budget_s=_remaining() - 10,
                                       rich=rich),
                min_needed=20.0)
    if r is not None:
        detail["tpch_sf1_suite_host"] = r
    r = section("tpcds_host", lambda: run_tpcds_trio(TPCDS_DATA),
                min_needed=15.0)
    if r is not None:
        detail["tpcds_host"] = r
    r = section("laion_host", lambda: run_laion(LAION_DATA), min_needed=15.0)
    if r is not None:
        detail["laion_host"] = r

    if os.path.isdir(os.path.join(SF10_DATA, "lineitem")) \
            and os.environ.get("BENCH_SKIP_SF10") != "1":
        # last: whatever global budget is left, queries past it are named
        # reserve the worst observed single SF10 query (~90s) so the
        # last query to START cannot push the emit past the window
        r = section("tpch_sf10_suite_host",
                    lambda: run_tpch_suite(SF10_DATA,
                                           budget_s=_remaining() - 100,
                                           rich=True),
                    min_needed=110.0)
        if r is not None:
            detail["tpch_sf10_suite_host"] = r
            from daft_tpu.execution import governor as _gov
            # per-query bookends reset the peak, so the suite-wide max
            # is the max over the per-query peaks, not the live gauge
            detail["rss_peak_bytes"] = max(
                [int(q.get("rss_peak_bytes", 0))
                 for q in r.get("per_query", {}).values()]
                + [int(_gov.peak_rss_bytes())])

    # errors that older rounds buried inside detail dicts surface here too
    for k, v in list(detail.items()):
        if isinstance(v, dict) and "error" in v:
            errors.setdefault(k, v["error"])

    # Full detail goes to a file; stdout's LAST line is a compact summary.
    # Four rounds of driver artifacts failed to parse because the final JSON
    # line (~10 KB) overflowed the driver's 2000-char tail window — the
    # driver only sees the tail, so the line must stay well under that.
    full = {
        "metric": f"tpch_q1_sf{SF}_rows_per_sec_per_chip",
        "value": round(nrows / ours, 1),
        "unit": "rows/s",
        "vs_baseline": round(base_s / ours, 3),
        "detail": detail,
    }
    if skipped:
        full["skipped_sections"] = skipped
    if errors:
        full["section_errors"] = errors
    full["elapsed_s"] = round(time.time() - _T0, 1)

    results_dir = os.path.join(REPO, "benchmarking", "results")
    os.makedirs(results_dir, exist_ok=True)
    artifact = os.path.join(results_dir, "r23_bench_driver.json")
    with open(artifact, "w") as f:
        json.dump(full, f, indent=1)
    # progress/bulk lines first (NOT last): full detail for humans reading
    # the whole log, then the parseable compact line closes stdout
    print("bench detail written to " + artifact, flush=True)

    def _suite_total(d):
        return d.get("total_hot_s") if isinstance(d, dict) else None

    fam: dict = {}
    for side in ("host", "device"):
        q1k = f"{side}_q1_median_s"
        if q1k in detail:
            fam.setdefault("q1_sf1", {})[side] = detail[q1k]
            fam["q1_sf1"][f"{side}_spread"] = detail[f"{side}_q1_spread_s"]
        s = _suite_total(detail.get(f"tpch_sf1_suite_{side}"))
        if s is not None:
            fam.setdefault("tpch_sf1_22q", {})[side] = s
        s = _suite_total(detail.get(f"tpch_sf10_suite_{side}"))
        if s is not None:
            fam.setdefault("tpch_sf10", {})[side] = s
        lai = detail.get(f"laion_{side}")
        if isinstance(lai, dict) and "images_per_s" in lai:
            fam.setdefault("laion_img_per_s", {})[side] = lai["images_per_s"]
        ds = detail.get(f"tpcds_{side}")
        if isinstance(ds, dict) and not ds.get("error"):
            tot = sum(v for v in ds.values() if isinstance(v, (int, float)))
            fam.setdefault("tpcds_trio", {})[side] = round(tot, 3)

    compact = {
        "metric": full["metric"], "value": full["value"],
        "unit": "rows/s", "vs_baseline": full["vs_baseline"],
        "q1_winner": detail.get("q1_winner"),
        "families": fam,
        "backend": detail.get("backend"),
        "artifact": os.path.relpath(artifact, REPO),
        "elapsed_s": full["elapsed_s"],
    }
    m = detail.get("mfu_device")
    if isinstance(m, dict) and "error" not in m:
        compact["mfu"] = {
            "agg_mfu_pct": m.get("grouped_agg", {}).get("mfu_pct"),
            "agg_roofline_pct": m.get("grouped_agg", {}).get(
                "roofline_pct"),
            "join_roofline_pct": m.get("join", {}).get("roofline_pct"),
            "argsort_roofline_pct": m.get("argsort", {}).get(
                "roofline_pct"),
        }
    led = detail.get("mfu_ledger_device")
    if isinstance(led, dict) and led:
        compact["ledger_dispatches"] = {
            k: v.get("dispatches") for k, v in led.items()}
    ch = detail.get("chaos")
    if isinstance(ch, dict) and "error" not in ch:
        compact["chaos"] = {
            "match": ch.get("match"),
            "events": sum(ch.get("recovery_events", {}).values())}
    sb = detail.get("shuffle_bench")
    if isinstance(sb, dict) and "error" not in sb:
        compact["shuffle"] = {
            "rows_per_s": sb["fast_path"]["rows_per_s"],
            "wire_saved": sb.get("wire_bytes_saved_ratio"),
            "combine_x": sb["fast_path"].get("combine_reduction"),
            "fetch_speedup": sb.get("fetch_overlap", {}).get("speedup")}
    me = detail.get("mesh_exchange_bench")
    if isinstance(me, dict) and "error" not in me:
        compact["mesh"] = {
            "coll_x": me.get("collective_speedup_vs_flight"),
            "hier_x": me.get("hierarchical_speedup_vs_flight"),
            "parity": all(me.get("parity", {}).values()),
            "hier_streams": me.get("streams", {}).get("hierarchical")}
    sc = detail.get("scan_bench")
    if isinstance(sc, dict) and "error" not in sc:
        compact["scan"] = {
            "req_reduction": sc.get("request_reduction"),
            "speedup": sc.get("scan_speedup"),
            "match": sc.get("answers_match")}
    sp = detail.get("spill_bench")
    if isinstance(sp, dict) and "error" not in sp:
        compact["spill"] = {
            "join_match": sp.get("join_match"),
            "agg_match": sp.get("agg_match"),
            "bytes": sp.get("spill_bytes_written"),
            "recursions": sp.get("recursions"),
            "slowdown_x": sp.get("slowdown_x"),
            "fast_x": sp.get("fast_vs_legacy_wall_x"),
            "disk_ratio": sp.get("fast_vs_legacy_disk_ratio")}
    ad = detail.get("adaptive_bench")
    if isinstance(ad, dict) and "error" not in ad:
        compact["adaptive"] = {
            "gate_pass": ad.get("gate_pass"),
            "cal_speedup_x": ad.get("calibrated", {}).get("speedup_x"),
            "match": ad.get("replan", {}).get("match"),
            "cal_decision_changed":
                ad.get("calibrated", {}).get("decision_changed"),
            "ndv_ratio":
                ad.get("calibrated", {}).get("observed_ndv_ratio")}
    kb = detail.get("kernels_bench")
    if isinstance(kb, dict) and "error" not in kb:
        compact["kernels"] = {
            "parity": kb.get("parity_all"),
            "contracts": kb.get("dispatch_contracts", {}).get("clean"),
            "agg_x": kb.get("agg_improvement_vs_sort"),
            "join_x": kb.get("join_improvement_vs_sort")}
    sv = detail.get("serve_bench")
    if isinstance(sv, dict) and "error" not in sv:
        compact["serve"] = {
            "qps": sv.get("qps"),
            "p99_ms": sv.get("latency_p99_ms"),
            "repeat_x": sv.get("repeat_speedup"),
            "rc_hit": sv.get("result_cache_hit_rate"),
            "leak": sv.get("admitted_bytes_outstanding_after_drain")}
    fl = detail.get("fleet_bench")
    if isinstance(fl, dict) and "error" not in fl:
        compact["fleet"] = {
            "scaling_x": fl.get("scaling_x"),
            "qps1": fl.get("single", {}).get("qps"),
            "qps3": fl.get("fleet3", {}).get("qps"),
            "rc_hit": fl.get("fleet3", {}).get("result_cache_hit_rate"),
            "cold_first": fl.get("cold_replica", {}).get(
                "first_query_result_cache")}
    ob = detail.get("obs_bench")
    if isinstance(ob, dict) and "error" not in ob:
        compact["obs"] = {
            "full_overhead_pct": ob.get("full", {}).get(
                "qps_overhead_pct"),
            "sampled_overhead_pct": ob.get("sampled", {}).get(
                "qps_overhead_pct"),
            "gate_pass": ob.get("gate_pass")}
    if skipped:
        compact["n_skipped"] = len(skipped)
    if errors:
        compact["n_errors"] = len(errors)
    # hard cap: drop optional keys until the line fits the driver's window
    for drop in ("obs", "fleet", "kernels", "serve", "scan", "adaptive",
                 "spill", "shuffle", "mesh", "chaos", "ledger_dispatches",
                 "mfu", "families", "q1_winner", "backend"):
        if len(json.dumps(compact)) <= 1500:
            break
        compact.pop(drop, None)
    line = json.dumps(compact)
    assert len(line) <= 1500, len(line)
    print(line)


if __name__ == "__main__":
    if "--device-child" in sys.argv:
        _device_child()
    elif "--device-pipeline-child" in sys.argv:
        _device_pipeline_child()
    elif "--mesh-exchange-child" in sys.argv:
        _mesh_exchange_child()
    elif "--fusion-child" in sys.argv:
        _fusion_child()
    elif "--warmup-child" in sys.argv:
        _warmup_child()
    elif "--fuzz-smoke" in sys.argv:
        # CI gate: differential plan fuzzer across all engine mode
        # matrices with the plan sanitizer armed — any mismatch vs the
        # unoptimized reference or plan-contract violation exits 1
        sys.exit(run_fuzz_smoke())
    elif "--scale-smoke" in sys.argv:
        # CI gate: forced-spill full 22-query suite at a small SF under
        # the sanitizer — wrong answers, RSS past the ceiling, leaked
        # spill files, or lock cycles exit 1
        sys.exit(run_scale_smoke())
    elif "--serve-smoke" in sys.argv:
        # CI gate: no datagen, no device tier — a few seconds of serving
        # traffic with leak + sanitizer-cycle checks
        sys.exit(run_serve_smoke())
    elif "--obs-smoke" in sys.argv:
        # CI gate: traced local + distributed queries, chrome-trace schema
        # validation, strict /metrics parse, flight-recorder rotation
        sys.exit(run_obs_smoke())
    elif "--fleet-smoke" in sys.argv:
        # CI gate: 3 real replica subprocesses behind the router; mixed
        # traffic + a mid-run kill and a graceful drain, with answer /
        # admission-leak / orphaned-session / lock-cycle checks
        sys.exit(run_fleet_smoke())
    else:
        main()
